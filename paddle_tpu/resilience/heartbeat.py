"""Worker heartbeats: the cluster Supervisor's liveness channel.

Each elastic worker runs ONE `HeartbeatWriter` thread that periodically
publishes a small JSON payload — step cursor, lifecycle status, the
plan generation it has acknowledged, watchdog state, reader positions —
to `hb_<worker_id>.json` under the cluster directory (a shared
filesystem, the same trust the checkpoint root already carries). Writes
are atomic (tmp + os.replace), so a reader never sees a torn payload;
each carries a monotonically increasing `seq` and the writer's
wall-clock time.

The coordinator side (`HeartbeatMonitor`) reads every heartbeat file
and classifies each worker:

  alive    — fresh payload (age <= timeout) with a live status
  dead     — payload older than the timeout, or (same host) the
             recorded pid no longer exists: SIGKILL'd, OOM'd, wedged
             hard enough that even the beat thread stopped. A worker
             whose last word was "done"/"left" is finished, not dead.
  fault    — the worker itself reported a cluster-level fault (e.g. a
             DispatchTimeoutError it chose to escalate instead of
             handling locally); it is still responsive.

Fault injection: an armed FaultPlan with a `heartbeat_stall@N` entry
makes `beat()` skip writes once the plan's step cursor passes N
(resilience/faults.py) — the deterministic way to prove the missed-
heartbeat detection path in CI without actually wedging a process.
"""
import json
import os
import socket
import threading
import time

from ..core.utils import atomic_write_json as _atomic_write_json
from . import faults as _faults

__all__ = ["HeartbeatWriter", "HeartbeatMonitor", "read_heartbeats",
           "heartbeat_path", "HB_PREFIX"]

HB_PREFIX = "hb_"

# lifecycle statuses a worker publishes; "done"/"left" are terminal and
# exempt from staleness (a finished worker stops beating by design)
TERMINAL_STATUSES = ("done", "left")


def heartbeat_path(cluster_dir, worker_id):
    return os.path.join(cluster_dir, "%s%s.json" % (HB_PREFIX, worker_id))


class HeartbeatWriter(object):
    """One worker's beat thread. `update(**fields)` changes the payload
    and beats immediately (acks must not wait an interval); the thread
    re-beats every `interval` seconds so the coordinator sees liveness
    even while the training loop is inside a long dispatch."""

    def __init__(self, cluster_dir, worker_id, interval=0.2):
        self.cluster_dir = str(cluster_dir)
        self.worker_id = str(worker_id)
        self.path = heartbeat_path(cluster_dir, worker_id)
        self.interval = float(interval)
        self._lock = threading.Lock()
        self._payload = {"worker_id": self.worker_id,
                         "pid": os.getpid(),
                         "host": socket.gethostname(),
                         "status": "joining",
                         "step": -1,
                         "gen": 0,
                         "gen_acked": 0,
                         # published so liveness readers that don't set
                         # a timeout (ptpu_elastic status) can scale
                         # their staleness window to THIS fleet's beat
                         # cadence instead of a fixed default
                         "interval": self.interval}
        self._seq = 0
        self._stop = threading.Event()
        self._thread = None
        os.makedirs(self.cluster_dir, exist_ok=True)

    # ----------------------------------------------------------- write --
    def beat(self):
        """Publish the current payload atomically. Honors an armed
        fault plan's heartbeat stall (the injected 'wedged host')."""
        plan = _faults.active_plan()
        if plan is not None and plan.heartbeat_stalled():
            return False
        with self._lock:
            self._seq += 1
            payload = dict(self._payload, seq=self._seq,
                           wall_time=time.time())
        try:
            # liveness signal, not durable state: no fsync (beats fire
            # every fraction of a second; a lost-on-power-cut beat is
            # indistinguishable from a missed one)
            _atomic_write_json(self.path, payload)
        except OSError:
            return False  # a missed beat is survivable; a crash is not
        return True

    def update(self, **fields):
        """Merge `fields` into the payload and beat NOW (plan acks and
        status transitions must reach the coordinator promptly)."""
        with self._lock:
            self._payload.update(fields)
        return self.beat()

    def snapshot(self):
        with self._lock:
            return dict(self._payload)

    # ------------------------------------------------------- lifecycle --
    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="ptpu-heartbeat-%s" % self.worker_id)
            self._thread.start()
        self.beat()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            self.beat()

    def close(self, status="left"):
        """Stop the thread and publish one final terminal beat, so the
        coordinator reads an orderly departure instead of a death."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.interval + 1.0)
        if status:
            self.update(status=status)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()


def _is_zombie(pid):
    """Linux: a SIGKILL'd child whose parent has not reaped it yet is
    state 'Z' in /proc/<pid>/stat — dead for every purpose that
    matters here. Platforms without /proc answer False (the staleness
    timeout still catches the death)."""
    try:
        with open("/proc/%d/stat" % pid) as f:
            fields = f.read()
        # state is the first field after the parenthesized comm (which
        # may itself contain spaces/parens)
        return fields.rpartition(")")[2].split()[0] == "Z"
    except (OSError, IndexError):
        return False


# ------------------------------------------------------------- monitor --
def read_heartbeats(cluster_dir):
    """{worker_id: payload} for every parseable heartbeat file. A
    half-written or vanished file is skipped (atomic replace makes that
    a transient, not a corruption)."""
    out = {}
    try:
        entries = os.listdir(cluster_dir)
    except OSError:
        return out
    for e in entries:
        if not e.startswith(HB_PREFIX) or not e.endswith(".json"):
            continue
        try:
            with open(os.path.join(cluster_dir, e)) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue
        wid = payload.get("worker_id")
        if wid:
            out[wid] = payload
    return out


class HeartbeatMonitor(object):
    """Coordinator-side view over the heartbeat directory."""

    def __init__(self, cluster_dir, timeout=3.0):
        self.cluster_dir = str(cluster_dir)
        self.timeout = float(timeout)
        self._host = socket.gethostname()

    def poll(self):
        """{worker_id: payload} with `age` and `alive` folded in."""
        now = time.time()
        beats = read_heartbeats(self.cluster_dir)
        for wid, hb in beats.items():
            hb["age"] = max(0.0, now - float(hb.get("wall_time", 0.0)))
            hb["alive"] = self._alive(hb)
        return beats

    def _alive(self, hb):
        if hb.get("status") in TERMINAL_STATUSES:
            return True  # finished, not dead — staleness is expected
        # same-host fast path: a SIGKILL'd worker is detected the
        # instant its pid vanishes, not a heartbeat-timeout later. A
        # zombie (dead but not yet reaped by its parent) still answers
        # kill(pid, 0) — on Linux, /proc exposes the truth.
        pid = hb.get("pid")
        if pid and hb.get("host") == self._host:
            try:
                os.kill(int(pid), 0)
                if _is_zombie(int(pid)):
                    return False
            except ProcessLookupError:
                return False
            except OSError:
                pass  # EPERM etc: alive under another uid
        return hb["age"] <= self.timeout

    def fleet_view(self):
        """The fleet gauge rows derived from the heartbeats — ONE
        implementation shared by `ptpu_elastic status` and the
        observability registry's cluster collector (two copies drifted
        once; never again): per worker the lifecycle status, liveness
        (the monitor's staleness/pid verdict), step cursor, steps
        behind the cohort's front-runner (None when the worker never
        reported a step), plan generations, beat age, the metrics port
        it published (if any), and the training-health fields
        (ARCHITECTURE.md §29): the worker's last sentinel status dict
        (z-scores, spike count), canary status dict, the fault repr a
        faulted worker escalated with, and the `sdc_device` a canary
        conviction named — the WHY behind a fence, not just the
        that."""
        beats = self.poll()
        # the front-runner is the furthest LIVE, still-participating
        # worker: a dead worker's stale file (nothing ever deletes it)
        # or a finished worker's terminal beat would otherwise pin
        # `front` past a rollback forever and every healthy worker
        # would read permanently behind
        live_steps = [int(b.get("step", -1)) for b in beats.values()
                      if int(b.get("step", -1)) >= 0 and b.get("alive")
                      and b.get("status") not in TERMINAL_STATUSES]
        front = max(live_steps) if live_steps else 0
        rows = []
        for wid, b in sorted(beats.items()):
            step = int(b.get("step", -1))
            rows.append({
                "worker": wid,
                "status": b.get("status"),
                "alive": bool(b.get("alive")),
                "step": step,
                "steps_behind": (max(0, front - step)
                                 if step >= 0 else None),
                "gen": int(b.get("gen", 0) or 0),
                "gen_acked": int(b.get("gen_acked", 0) or 0),
                "beat_age_s": float(b.get("age", 0.0)),
                "metrics_port": b.get("metrics_port"),
                "sentinel": b.get("sentinel"),
                "sdc": b.get("sdc"),
                "fault": b.get("fault"),
                "sdc_device": b.get("sdc_device"),
            })
        return rows

    def dead_workers(self, expected=None):
        """worker_ids considered dead: stale/vanished-pid heartbeats,
        plus any `expected` id that never wrote a heartbeat at all."""
        beats = self.poll()
        dead = [wid for wid, hb in beats.items() if not hb["alive"]]
        for wid in expected or ():
            if wid not in beats:
                dead.append(wid)
        return sorted(set(dead))
