"""Supervisor: the recovery policy engine around the training dispatch.

The TensorFlow-paper stance (arXiv:1605.08695) made concrete: detection
(device guards, hang watchdog, reader fault channel, host divergence) is
only half of fault tolerance — something must DECIDE. The Supervisor
owns the training loop and, per fault CLASS, applies a configured
escalation chain of actions:

    classes:  numeric   — NumericalGuardError (device guard trip) or
                          DivergenceFault (host EMA spike)
              hang      — DispatchTimeoutError (per-dispatch watchdog)
              reader    — reader-tagged failures (worker-thread errors,
                          injected reader faults)
              dispatch  — everything else raised by the dispatch
    actions:  skip_batch(times=)        exact for guard trips (updates
                                        were gated on device) and for
                                        reader faults (records dropped
                                        at known positions)
              retry(times=, backoff=)   re-attempt the same step
              rollback(times=, lr_scale=)  restore the newest valid
                                        PR-4 snapshot — params,
                                        accumulators, seed cursor,
                                        reader positions — then
                                        optionally damp the LR; a
                                        repeat rollback with no
                                        progress walks back a snapshot
              abort(bundle_dir=)        capture a diagnostic bundle and
                                        raise TrainingAborted

Every action lands in the structured event log (`sup.events`) and in
profiler tags (`resilience/<class>:<action>` rows in profile_report).
Budgets are consumed per class; when a chain runs dry the terminal
action is abort. Recovered-from faults leave training bit-exact where
the mechanism allows it (tests/unittests/test_resilience.py pins this):
a rollback-resumed run equals the fault-free run, and a skipped bad
batch equals a fault-free run that skipped the same batch.
"""
import collections
import time

import numpy as np

from .. import profiler as _prof
from ..observability import registry as _obsreg
from ..observability import trace as _otrace
from ..core import readers as _readers
from ..core.executor import (DispatchTimeoutError, NumericalGuardError,
                             global_scope)
from ..core.readers import EOFException
from . import faults as _faults
from . import watchdog as _watchdog
from .guards import DivergenceFault
from .sentinel import DivergenceError, LossSpikeError
from .sdc import SilentCorruptionError

__all__ = ["Supervisor", "TrainingAborted", "Action",
           "skip_batch", "retry", "rollback", "rollback_skip_data",
           "abort", "DEFAULT_POLICIES", "FAULT_CLASSES"]

FAULT_CLASSES = ("numeric", "hang", "reader", "dispatch",
                 "loss_spike", "divergence", "sdc")


class TrainingAborted(RuntimeError):
    """Terminal escalation: the configured chains are exhausted (or an
    abort action was reached). `bundle` is the diagnostic-bundle path
    when one was captured (feed ptpu_doctor.py), `cause` the original
    fault."""

    def __init__(self, message, bundle=None, cause=None):
        super(TrainingAborted, self).__init__(message)
        self.bundle = bundle
        self.cause = cause


class Action(object):
    """One escalation-chain entry. `times` is the per-class budget this
    action absorbs before the chain escalates past it."""

    __slots__ = ("kind", "times", "backoff", "lr_scale", "bundle_dir",
                 "skip")

    def __init__(self, kind, times=1, backoff=0.0, lr_scale=None,
                 bundle_dir=None, skip=0):
        self.kind = kind
        self.times = max(1, int(times))
        self.backoff = float(backoff)
        self.lr_scale = lr_scale
        self.bundle_dir = bundle_dir
        self.skip = max(0, int(skip))

    def __repr__(self):
        return "Action(%s, times=%d)" % (self.kind, self.times)


def skip_batch(times=1):
    """Drop the offending batch and move on. Exact for device-guard
    trips (the step's updates were already gated off on device) and for
    reader faults (the batch's records are consumed at known reader
    positions); best-effort for hang/dispatch faults."""
    return Action("skip_batch", times=times)


def retry(times=1, backoff=0.0):
    """Re-attempt the same step after `backoff` seconds (transient
    dispatch failures, brief stalls)."""
    return Action("retry", times=times, backoff=backoff)


def rollback(times=1, lr_scale=None):
    """Restore the newest valid checkpoint snapshot (full training
    state: params, accumulators, seed cursor, reader positions) and
    resume from it; `lr_scale` damps every persistable learning-rate
    var on re-entry (optimizer.scale_learning_rate)."""
    return Action("rollback", times=times, lr_scale=lr_scale)


def rollback_skip_data(times=1, skip=0, lr_scale=None):
    """The PaLM-style bad-batch remedy: restore the newest valid
    snapshot AND advance every in-graph reader stream past the
    offending batch window — the records the faulted attempt (and
    everything since the snapshot) consumed, plus `skip` further
    K-blocks for margin. The resumed run is bit-exact vs a from-scratch
    resume over a stream that never contained those records
    (tests/unittests/test_sentinel.py pins this). A feed-fed program
    (no readers) degrades to a plain rollback with a logged note."""
    return Action("rollback_skip", times=times, skip=skip,
                  lr_scale=lr_scale)


def abort(bundle_dir=None):
    """Capture a diagnostic bundle (to `bundle_dir`, falling back to the
    Supervisor's) and raise TrainingAborted."""
    return Action("abort", bundle_dir=bundle_dir)


DEFAULT_POLICIES = {
    "numeric": (skip_batch(times=2), rollback(times=2), abort()),
    # no retry for hangs: post-timeout device state is indeterminate
    # (DispatchTimeoutError's contract) — a retry would re-dispatch
    # against the wedged arrays and deterministically burn a second
    # full deadline before escalating anyway
    "hang": (rollback(times=2), abort()),
    "reader": (skip_batch(times=2), abort()),
    "dispatch": (retry(times=2, backoff=0.05), rollback(times=1), abort()),
    # sentinel classes (ARCHITECTURE.md §29). A loss spike's update
    # ALREADY landed (it is only visible after the fetch), so skip/
    # retry can't help: roll back and route the stream around the bad
    # window. Divergence is drift, not one batch — skipping data won't
    # fix it; rollback (configure lr_scale where the program has a
    # persistable LR) then abort. SDC is hardware: locally terminal —
    # the elastic worker escalates it so the coordinator quarantines
    # the device instead.
    "loss_spike": (rollback_skip_data(times=2), abort()),
    "divergence": (rollback(times=2), abort()),
    "sdc": (abort(),),
}


class Supervisor(object):
    def __init__(self, executor, program, scope=None,
                 checkpoint_manager=None, policies=None,
                 watchdog_timeout=None, divergence=None, bundle_dir=None,
                 metrics_window=64, restore_layout=None, sentinel=None,
                 sdc=None, sdc_every=64):
        """Wrap `executor` dispatches of `program` in detection +
        recovery. `policies` maps fault class -> escalation chain
        (missing classes use DEFAULT_POLICIES). `watchdog_timeout` arms
        the per-dispatch hang watchdog (seconds; None = off).
        `divergence` is a guards.DivergenceDetector fed every step's
        first fetch. `checkpoint_manager` enables rollback (and
        train(checkpoint_every=)); without one, rollback actions
        escalate straight past themselves. `restore_layout` (a
        parallel.DeviceLayout) makes every rollback restore reshard
        onto that target mesh — the elastic worker's setting, so a
        local rollback lands state exactly where the cohort's current
        mesh shape wants it. Registers itself on the reader fault
        channel so worker-thread errors surface in the event log the
        moment they happen.

        `sentinel` (a sentinel.TrainingSentinel) is fed every healthy
        step's first fetch plus the executor's guard-stat grad norm
        (`last_stats`, populated when guards were installed with
        grad_norm=True); its detections route through the loss_spike/
        divergence fault classes. `sdc` (an sdc.CanaryChecker) runs a
        deterministic canary dispatch every `sdc_every` completed
        steps; a digest mismatch routes through the sdc class."""
        self.exe = executor
        self.program = program
        # ParallelExecutor owns its scope and takes no program/scope per
        # call — adapt the dispatch instead of asking callers to
        self._is_parallel = not hasattr(executor, "place")
        if scope is None and self._is_parallel:
            scope = getattr(executor, "_scope", None)
        self.scope = scope if scope is not None else global_scope()
        self.ckpt = checkpoint_manager
        self.policies = dict(DEFAULT_POLICIES)
        for cls, chain in (policies or {}).items():
            if cls not in FAULT_CLASSES:
                raise ValueError("unknown fault class %r (known: %s)"
                                 % (cls, ", ".join(FAULT_CLASSES)))
            self.policies[cls] = tuple(chain)
        # lr_scale needs a persistable LR var: fail HERE, at
        # construction, not from inside the first real fault's recovery
        # (a scheduler-derived rate is recomputed in-graph every step
        # and cannot be damped by scaling scope state)
        if any(a.kind in ("rollback", "rollback_skip")
               and a.lr_scale is not None
               for chain in self.policies.values() for a in chain):
            from ..optimizer import persistable_lr_names
            if not persistable_lr_names(program):
                raise ValueError(
                    "rollback(lr_scale=...) configured but the program "
                    "has no persistable learning-rate variable to scale "
                    "(scheduler-derived rates are recomputed in-graph; "
                    "build with a float learning_rate to use lr_scale)")
        self.watchdog_timeout = watchdog_timeout
        self.divergence = divergence
        self.sentinel = sentinel
        self.sdc = sdc
        self.sdc_every = None if not sdc_every else max(1, int(sdc_every))
        self._sdc_last = 0
        self.bundle_dir = bundle_dir
        self.restore_layout = restore_layout
        self.step = 0          # completed training steps (save label)
        self.events = []       # structured recovery log
        self.metrics = collections.deque(maxlen=int(metrics_window))
        self._chain_pos = {}   # class -> [chain index, uses of current]
        self._last_restore_step = None
        self._made_progress = True
        self._closed = False
        self._prev_listener = _readers.set_fault_listener(
            self._on_reader_fault)

    # ------------------------------------------------------- lifecycle --
    def close(self):
        if not self._closed:
            self._closed = True
            _readers.set_fault_listener(self._prev_listener)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---------------------------------------------------------- events --
    def _log(self, cls, action, detail=None, error=None, seconds=0.0,
             **extra):
        ev = {"step": int(self.step), "class": cls, "action": action,
              "detail": detail,
              "error": None if error is None else repr(error),
              "wall_time": time.time()}
        ev.update(extra)
        self.events.append(ev)
        # always-on observability (ARCHITECTURE.md §24): every recovery
        # action is an instant event in the flight recorder (it lands in
        # the same timeline as the dispatch spans it interrupted — a
        # bundle shows the guard trip BETWEEN the steps) and a labeled
        # counter on /metrics
        _otrace.instant("resilience/%s:%s" % (cls, action),
                        cat="resilience", step=int(self.step),
                        error=ev["error"])
        _obsreg.REGISTRY.counter(
            "ptpu_supervisor_events_total",
            "supervisor recovery events by fault class and action"
        ).inc(**{"class": cls, "action": action})
        if _prof.is_active():
            # same gate as the executors' record_run: profiler rows
            # reflect the profiled window, the event log keeps everything
            _prof.record_event("resilience/%s:%s" % (cls, action),
                               seconds)
        return ev

    def _on_reader_fault(self, reader, exc):
        """Reader fault channel (worker thread): log IMMEDIATELY — the
        raise will reach the loop at the next read, but the supervisor
        (and anyone tailing the event log) knows now."""
        self._log("reader", "notified", error=exc,
                  detail="worker-thread fault in %s" % type(reader).__name__)

    # ----------------------------------------------------------- steps --
    def run_step(self, feed=None, fetch_list=None, steps=1,
                 fetch_reduce="stack", **run_kw):
        """One supervised step (or K-step block with steps=K). Returns
        the fetches, or None when no fetches exist for this call:
        either the step was SKIPPED (self.step advanced past it) or a
        ROLLBACK rewound self.step — compare self.step to tell, and
        after a rollback re-derive `feed` for the new step index before
        calling again (a rolled-back attempt never re-dispatches the
        stale feed; train() does this re-derivation automatically).
        Raises EOFException at end of data and TrainingAborted at
        terminal escalation; everything else is handled per policy."""
        while True:
            plan = _faults.active_plan()
            if plan is not None:
                plan.set_step(self.step)
            t0 = time.perf_counter()
            try:
                if self._is_parallel:
                    fetches = self.exe.run(
                        fetch_list or [], feed=feed, steps=steps,
                        fetch_reduce=fetch_reduce,
                        timeout=self.watchdog_timeout, **run_kw)
                else:
                    fetches = self.exe.run(
                        self.program, feed=feed, fetch_list=fetch_list,
                        scope=self.scope, steps=steps,
                        fetch_reduce=fetch_reduce,
                        timeout=self.watchdog_timeout, **run_kw)
            except EOFException:
                raise
            except Exception as e:  # noqa: BLE001 — classified below
                if getattr(e, "_cluster_fence", False):
                    # a cluster fence is not a fault: the coordinator
                    # moved the plan and THIS process must reconfigure —
                    # hand it up to the elastic worker loop untouched
                    # (nothing was consumed: the barrier fires before
                    # the prepass and seed draw)
                    raise
                outcome = self._handle_fault(self._classify(e), e,
                                             feed=feed, steps=steps)
                if outcome == "skip":
                    self.step += steps
                    self._made_progress = True
                    return None
                if outcome == "rolled_back":
                    # self.step rewound: this call's feed belongs to the
                    # OLD index — hand control back so the caller feeds
                    # the restored step, never the stale batch
                    return None
                continue  # retry: same step, same feed
            # healthy dispatch: host-side divergence check on fetch 0
            detail = None
            fetch0 = None
            if fetches:
                fetch0 = float(np.mean(np.asarray(fetches[0])))
            if self.divergence is not None and fetch0 is not None:
                detail = self.divergence.update(fetch0)
            if detail is not None:
                outcome = self._handle_fault(
                    "numeric", DivergenceFault(detail), feed=feed,
                    steps=steps, applied=True)
                if outcome == "rolled_back":
                    return None  # caller re-feeds the restored step
                # skip/retry cannot undo an applied update: accept the
                # step (the event log carries the warning) and move on
            if self.sentinel is not None and fetch0 is not None:
                # the grad-norm scalar rode the guard stat channel in
                # the dispatch that just returned (Executor.last_stats)
                # — materializing it here syncs an already-computed
                # device scalar, not a new program output
                gn = None
                stats = getattr(self.exe, "last_stats", None) or {}
                if "grad_norm" in stats:
                    gn = float(np.asarray(stats["grad_norm"]))
                err = self.sentinel.observe(fetch0, grad_norm=gn,
                                            step=self.step)
                if err is not None:
                    outcome = self._handle_fault(
                        self._classify(err), err, feed=feed,
                        steps=steps, applied=True)
                    if outcome == "rolled_back":
                        return None  # caller re-feeds the restored step
            if fetch0 is not None:
                self.metrics.append(
                    {"step": int(self.step), "fetch0": fetch0,
                     "seconds": time.perf_counter() - t0})
            self.step += steps
            self._made_progress = True
            if self.sdc is not None and self.sdc_every \
                    and self.step - self._sdc_last >= self.sdc_every:
                self._sdc_last = self.step
                try:
                    self.sdc.check()
                except SilentCorruptionError as e:
                    outcome = self._handle_fault("sdc", e, feed=feed,
                                                 steps=steps,
                                                 applied=True)
                    if outcome == "rolled_back":
                        return None
            return fetches

    def train(self, num_steps, feed_fn=None, fetch_list=None, steps=1,
              fetch_reduce="stack", checkpoint_every=None):
        """Drive the supervised loop until `num_steps` training steps
        complete (EOF ends it early, cleanly). `feed_fn(step_index)`
        must be a deterministic function of the index — after a rollback
        the loop re-asks for the replayed indices. With a checkpoint
        manager, `checkpoint_every=E` snapshots at every E-step
        boundary. Returns [{"step", "fetches"}] per block attempt that
        completed or was skipped (replayed indices appear again, in
        order — the event log tells the story)."""
        results = []
        try:
            while self.step < num_steps:
                idx = self.step
                feed = feed_fn(idx) if feed_fn is not None else None
                out = self.run_step(feed=feed, fetch_list=fetch_list,
                                    steps=steps,
                                    fetch_reduce=fetch_reduce)
                if self.step <= idx:
                    continue  # rolled back: re-derive feed for new index
                results.append({"step": idx, "fetches": out})
                if (checkpoint_every and self.ckpt is not None
                        and self.step // int(checkpoint_every)
                        > idx // int(checkpoint_every)):
                    self.ckpt.save(self.step, program=self.program,
                                   scope=self.scope)
        except EOFException:
            self._log("reader", "eof", detail="end of data")
        return results

    # ------------------------------------------------------ escalation --
    def _classify(self, exc):
        if isinstance(exc, LossSpikeError):
            return "loss_spike"
        if isinstance(exc, DivergenceError):
            return "divergence"
        if isinstance(exc, SilentCorruptionError):
            return "sdc"
        if isinstance(exc, (NumericalGuardError, DivergenceFault)):
            return "numeric"
        if isinstance(exc, DispatchTimeoutError):
            return "hang"
        if getattr(exc, "_reader_fault", False):
            return "reader"
        return "dispatch"

    def _next_action(self, cls):
        chain = self.policies.get(cls) or (abort(),)
        pos = self._chain_pos.setdefault(cls, [0, 0])
        while pos[0] < len(chain):
            act = chain[pos[0]]
            if act.kind == "abort" or pos[1] < act.times:
                pos[1] += 1
                return act
            pos[0] += 1
            pos[1] = 0
        return Action("abort")

    def _handle_fault(self, cls, exc, feed=None, steps=1, applied=False):
        """Apply the next action of `cls`'s chain. Returns "skip",
        "retry" or "rolled_back"; raises TrainingAborted at the end of
        every chain. A hang trip captures its diagnostic bundle BEFORE
        escalating (the wedged state is the evidence; an abort for the
        same fault reuses that capture instead of writing a second).
        `applied=True` marks faults whose step's updates already landed
        (host divergence): skip/retry can't undo those — they log
        honestly, consume their budget (repeat divergence escalates
        toward rollback) and do nothing else."""
        bundle = None
        if cls == "hang" and self.bundle_dir:
            bundle = _watchdog.write_bundle(
                self.bundle_dir, "hang watchdog tripped", fault_class=cls,
                step=self.step, program=self.program, feed=feed,
                scope=self.scope, metrics=self.metrics,
                events=self.events, error=exc)
            self._log(cls, "bundle", detail=bundle, error=exc)
        while True:
            t0 = time.perf_counter()
            act = self._next_action(cls)
            if act.kind == "skip_batch":
                detail = None
                if applied:
                    detail = ("update already applied (divergence); "
                              "tolerated — budget consumed, repeats "
                              "escalate")
                elif cls != "numeric":
                    # a guard trip already consumed its records (and
                    # gated its updates); everything else must drop the
                    # batch at the readers' known positions to skip it
                    dropped, want = self._drop_batch(steps)
                    if dropped < want:
                        # a record the source refuses to produce cannot
                        # be dropped: say so — the next attempt faults
                        # again and the budgeted chain escalates
                        detail = ("dropped %d/%d records; the reader "
                                  "source is failing" % (dropped, want))
                self._log(cls, "skip_batch", error=exc, detail=detail,
                          seconds=time.perf_counter() - t0)
                return "skip"
            if act.kind == "retry":
                if applied:
                    self._log(cls, "retry", error=exc,
                              detail="update already applied "
                                     "(divergence); nothing to retry — "
                                     "budget consumed, repeats escalate",
                              seconds=time.perf_counter() - t0)
                    return "skip"
                if act.backoff > 0:
                    time.sleep(act.backoff)
                self._log(cls, "retry", error=exc,
                          detail="backoff %.3fs" % act.backoff,
                          seconds=time.perf_counter() - t0)
                return "retry"
            if act.kind == "rollback":
                restored = self._rollback(act, exc, t0)
                if restored is None:
                    continue  # no manager / no snapshot: escalate
                return "rolled_back"
            if act.kind == "rollback_skip":
                restored = self._rollback_skip(act, exc, t0, steps)
                if restored is None:
                    continue  # no manager / no snapshot: escalate
                return "rolled_back"
            # abort (also the terminal fallthrough)
            bdir = act.bundle_dir or self.bundle_dir
            if bundle is None and bdir:
                bundle = _watchdog.write_bundle(
                    bdir, "escalation chain aborted", fault_class=cls,
                    step=self.step, program=self.program, feed=feed,
                    scope=self.scope, metrics=self.metrics,
                    events=self.events, error=exc)
            self._log(cls, "abort", detail=bundle, error=exc,
                      seconds=time.perf_counter() - t0)
            raise TrainingAborted(
                "training aborted at step %d on a %s fault: %r%s"
                % (self.step, cls, exc,
                   " (diagnostic bundle: %s)" % bundle if bundle else ""),
                bundle=bundle, cause=exc)

    def _rollback(self, act, exc, t0):
        if self.ckpt is None:
            self._log("_", "rollback_unavailable",
                      detail="no checkpoint manager", error=exc)
            return None
        # never restore PAST the current position: a checkpoint dir
        # holding newer snapshots (stale dir, walked-back state) must
        # not jump training forward. A repeat rollback that made no
        # progress past its last restore additionally walks back one
        # snapshot (the newest may be poisoned).
        bound = self.step + 1
        before = bound if self._made_progress else min(
            self._last_restore_step, bound)
        restored = self.ckpt.restore(program=self.program,
                                     scope=self.scope, before=before,
                                     layout=self.restore_layout)
        if restored is None:
            self._log("_", "rollback_unavailable",
                      detail="no valid snapshot%s" % (
                          " before step %d" % before if before else ""),
                      error=exc)
            return None
        self.step = int(restored)
        self._last_restore_step = int(restored)
        self._made_progress = False
        scaled = None
        if act.lr_scale is not None:
            from ..optimizer import scale_learning_rate
            try:
                scaled = scale_learning_rate(self.program, self.scope,
                                             act.lr_scale)
            except ValueError as se:
                # construction-time validation should have caught this;
                # mid-recovery the restore already happened, so continue
                # un-damped (budgets still bound the loop) rather than
                # crash out of the handler with no abort and no bundle
                self._log("_", "lr_scale_failed", error=se)
        if self.divergence is not None:
            self.divergence.reset()
        if self.sentinel is not None:
            # the restored state replays an earlier stream — the
            # window's samples come from a future that will now unfold
            # differently, so the baseline restarts (warmup included)
            self.sentinel.reset()
        self._log(self._classify(exc), "rollback", error=exc,
                  detail="restored step %d%s" % (
                      restored,
                      "; lr x%g on %s" % (act.lr_scale, scaled)
                      if scaled else ""),
                  seconds=time.perf_counter() - t0)
        return restored

    def _reader_states(self):
        """(name, state) per distinct in-graph reader with a position
        cursor — the PR-4 machinery rollback_skip_data rides."""
        out, seen = [], set()
        for op in self.program.global_block().ops:
            if op.type != "read":
                continue
            name = op.inputs["Reader"][0]
            if name in seen:
                continue
            seen.add(name)
            state = self.scope.get(name)
            if state is not None and hasattr(state, "_consumed"):
                out.append((name, state))
        return out

    def _rollback_skip(self, act, exc, t0, steps):
        """rollback_skip_data: capture every reader's CURRENT position
        (one past the offending window — the records of the faulted
        attempt are already consumed when a spike is observed), restore
        the newest snapshot (which rewinds the readers to the
        snapshot's positions), then advance each stream back to the
        captured position plus `act.skip` further K-blocks. The resumed
        run therefore trains over exactly the stream a from-scratch
        resume that never saw those records would: restore + skip is
        deterministic replay, not approximation."""
        readers = self._reader_states()
        targets = {n: int(s._consumed) + act.skip * int(steps)
                   for n, s in readers}
        restored = self._rollback(act, exc, t0)
        if restored is None:
            return None
        from ..checkpoint.manager import skip_reader_records
        want = {}
        for n, _ in readers:
            state = self.scope.get(n)
            if state is None or not hasattr(state, "_consumed"):
                continue
            want[n] = max(0, targets[n] - int(state._consumed))
        # EOF while skipping propagates: end of data, the caller's
        # loop ends cleanly
        total = skip_reader_records(self.scope, want, want)
        detail = ("skipped %d records across %d reader(s) past the "
                  "fault window (skip=%d x steps=%d)"
                  % (total, len(readers), act.skip, int(steps))
                  if readers else
                  "no in-graph readers: degraded to a plain rollback "
                  "(feed-fed program — the caller's feed_fn decides "
                  "what the restored step sees)")
        self._log(self._classify(exc), "rollback_skip", error=exc,
                  detail=detail, seconds=time.perf_counter() - t0)
        return restored

    def _drop_batch(self, steps):
        """Consume (and discard) the records the failed attempt would
        have trained on — one K-block per in-graph reader, at the
        readers' current (exactly known) positions, record by record so
        a single raising record doesn't refund the whole block
        (next_many's atomicity is exactly wrong here: the good records
        around a bad one SHOULD be dropped). Returns (dropped, wanted)
        summed over all readers — a record the source refuses to
        produce never materialized, so it cannot be counted as dropped.
        A clean EOF propagates (end of data, not a fault); a feed-fed
        program (no readers) returns (0, 0)."""
        dropped = wanted = 0
        for op in self.program.global_block().ops:
            if op.type != "read":
                continue
            state = self.scope.get(op.inputs["Reader"][0])
            if state is None:
                continue
            for _ in range(int(steps)):
                wanted += 1
                try:
                    state.next()
                    dropped += 1
                except EOFException:
                    raise
                except Exception:
                    pass  # the raising record IS the fault being skipped
        return dropped, wanted
