"""Model persistence: save/load params, persistables, inference models,
checkpoints.

Parity: python/paddle/fluid/io.py. Storage format is a directory of .npy
files (one per var, like the reference's one-file-per-var LoDTensor dumps)
plus a JSON manifest; `save_inference_model` prunes to the fetch subgraph
(Program.prune) and stores it in the versioned self-describing desc format
(core/program_desc.py — the reference's ProgramDesc proto equivalent).
Training checkpoints are a first-class subsystem now: `save_checkpoint`/
`load_checkpoint` below are deprecation shims over
`paddle_tpu.checkpoint.CheckpointManager` (atomic async snapshots, hash
verification, retention, bit-exact resume — ARCHITECTURE.md §16).
"""
import json
import os
import pickle

import numpy as np

from .core.framework import Program, Parameter, Variable, default_main_program
from .core.executor import global_scope
from .core import program_desc as _program_desc

__all__ = [
    "save_vars", "save_params", "save_persistables", "load_vars",
    "load_params", "load_persistables", "save_inference_model",
    "load_inference_model", "load_reference_model",
    "save_reference_model",
    "get_inference_program",
    "save_checkpoint", "load_checkpoint",
    "get_parameter_value", "get_parameter_value_by_name",
]


def is_persistable(var):
    return var.persistable


def is_parameter(var):
    return isinstance(var, Parameter)


def _var_list(main_program, predicate, vars):
    if vars is not None:
        return [v if isinstance(v, Variable) else
                main_program.global_block().var(v) for v in vars]
    if main_program is None:
        main_program = default_main_program()
    return [v for v in main_program.list_vars() if predicate(v)]


def _reader_var_names(program):
    """Names wired into host-io (reader) ops anywhere in `program`.
    In-graph reader vars are persistable but their scope value is a
    host-side ReaderState, not a tensor — runtime plumbing, never
    checkpoint payload, on both the save and load side. Detected from
    the OPS (not the `reader_shapes` attribute layers/io.py sets) so the
    classification survives a program_desc serialization round trip."""
    from .core import readers as _readers
    names = set()
    if program is None:
        return names
    for block in program.blocks:
        for op in block.ops:
            if op.type == "read" or _readers.is_host_io_op(op.type):
                for slot in list(op.inputs.values()) + \
                        list(op.outputs.values()):
                    if op.type == "read" and slot is op.outputs.get("Out"):
                        continue  # the data outputs ARE tensors
                    names.update(slot)
    return names


def _is_reader_var(v, reader_names=()):
    return hasattr(v, "reader_shapes") or v.name in reader_names


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None, allow_missing=False):
    """Write `vars` (or the program's persistables) as .npy files + a JSON
    manifest. A var that has NO value in the scope is checkpoint
    corruption — the file set would silently omit a parameter and a later
    load would leave it at init — so it raises unless `allow_missing=True`
    (the legacy lenient behavior, for intentionally partial saves)."""
    vars = _var_list(main_program, predicate or is_persistable, vars)
    scope = global_scope()
    reader_names = _reader_var_names(main_program)
    from .core.readers import ReaderBase
    # resolve and CHECK every var before the first byte is written: a
    # raise mid-write into a pre-existing checkpoint dir would leave the
    # old manifest pointing at a mix of new and old arrays — silent
    # corruption a later load couldn't detect. Values stay unconverted
    # here (np.asarray of a device array copies to host; doing that for
    # ALL vars up front would pin the whole checkpoint in host memory).
    to_write = []
    for v in vars:
        val = scope.get(v.name)
        if val is None:
            if allow_missing or _is_reader_var(v, reader_names):
                continue
            raise RuntimeError(
                "save_vars: variable %r has no value in the current scope "
                "— saving would silently omit it from the checkpoint. Run "
                "the startup program first, or pass allow_missing=True "
                "for an intentionally partial save." % v.name)
        if isinstance(val, ReaderBase):
            continue  # live reader state: runtime plumbing, not a tensor
        to_write.append((v, val))
    os.makedirs(dirname, exist_ok=True)
    manifest = {}
    for v, val in to_write:
        arr = np.asarray(val)
        safe = v.name.replace("/", "__")
        np.save(os.path.join(dirname, safe + ".npy"), arr)
        manifest[v.name] = {"file": safe + ".npy", "shape": list(arr.shape),
                            "dtype": str(arr.dtype),
                            "is_param": is_parameter(v)}
    with open(os.path.join(dirname, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1)


def save_params(executor, dirname, main_program=None, vars=None,
                filename=None, allow_missing=False):
    save_vars(executor, dirname, main_program, vars, is_parameter, filename,
              allow_missing=allow_missing)


def save_persistables(executor, dirname, main_program=None, filename=None,
                      allow_missing=False):
    save_vars(executor, dirname, main_program, None, is_persistable, filename,
              allow_missing=allow_missing)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None, params_only=False,
              allow_missing=False):
    """Restore vars from a save_vars directory. A requested var that the
    manifest does NOT carry would silently stay at its init value — the
    classic corrupted-resume — so it raises unless `allow_missing=True`
    (legacy lenient behavior, for deliberately partial restores)."""
    with open(os.path.join(dirname, "manifest.json")) as f:
        manifest = json.load(f)
    scope = global_scope()
    want = None
    if vars is not None or main_program is not None:
        reader_names = _reader_var_names(main_program)
        want = set(v.name for v in
                   _var_list(main_program, predicate or is_persistable, vars)
                   if not _is_reader_var(v, reader_names))
    # strict check BEFORE the first scope.set: raising half-restored
    # would leave a mix of loaded and stale values behind for a caller
    # that catches the error — the load-side twin of save_vars' rule
    if want is not None and not allow_missing:
        absent = sorted(want - set(manifest))
        if absent:
            raise RuntimeError(
                "load_vars: %d requested variable(s) are not in the "
                "manifest at %r and would silently keep their init "
                "values: %s. Pass allow_missing=True for an "
                "intentionally partial restore."
                % (len(absent), dirname, absent))
    for name, meta in manifest.items():
        if want is not None and name not in want:
            continue
        if params_only and want is None and not meta.get("is_param", True):
            continue  # no program to filter by: fall back to manifest kinds
        arr = np.load(os.path.join(dirname, meta["file"]))
        scope.set(name, arr)


def load_params(executor, dirname, main_program=None, filename=None,
                allow_missing=False):
    load_vars(executor, dirname, main_program, None, is_parameter, filename,
              params_only=True, allow_missing=allow_missing)


def load_persistables(executor, dirname, main_program=None, filename=None,
                      allow_missing=False):
    load_vars(executor, dirname, main_program, None, is_persistable, filename,
              allow_missing=allow_missing)


def get_inference_program(target_vars, main_program=None):
    if main_program is None:
        main_program = default_main_program()
    return main_program.clone(for_test=True)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None):
    """Parity: fluid.io.save_inference_model — prunes to the inference
    sub-graph (Program.prune: backward/optimizer ops and unrelated branches
    dropped), stores the versioned program desc + only the params the
    pruned graph reads."""
    if main_program is None:
        main_program = default_main_program()
    target_names = [v if isinstance(v, str) else v.name for v in target_vars]
    inference_program = main_program.prune(target_names, for_test=True)
    os.makedirs(dirname, exist_ok=True)
    meta = {"feed": list(feeded_var_names), "fetch": target_names}
    with open(os.path.join(dirname, "__model_meta__.json"), "w") as f:
        json.dump(meta, f)
    with open(os.path.join(dirname, model_filename or "__model__"), "wb") as f:
        f.write(_program_desc.program_to_bytes(inference_program))
    save_params(executor, dirname, inference_program)
    return inference_program


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    with open(os.path.join(dirname, model_filename or "__model__"), "rb") as f:
        raw = f.read()
    if raw[:1] == b"\x80":  # pickle protocol >= 2: round-1 legacy artifact
        program = pickle.loads(raw)
        program._uid = next(Program._uid_counter)  # predates _uid; no id()
        if not hasattr(program, "_accumulator_owner"):  # also predates it
            program._accumulator_owner = {}
    else:
        program = _program_desc.program_from_bytes(raw)
    with open(os.path.join(dirname, "__model_meta__.json")) as f:
        meta = json.load(f)
    # strict mode (FLAGS_validate_program=1, same gate as Executor.run —
    # literally the same flag resolver, so strictness can't drift):
    # a malformed saved model is rejected HERE with structured
    # Diagnostics, before params load or any request traces it.
    # serving.InferenceEngine validates unconditionally.
    from .core.executor import _validate_program_flag
    if _validate_program_flag():
        from .analysis import validate_or_raise
        validate_or_raise(program, feed_names=meta["feed"],
                          fetch_names=meta["fetch"])
    load_params(executor, dirname)
    fetch_vars = [program.global_block().var(n) for n in meta["fetch"]]
    return program, meta["feed"], fetch_vars


def save_reference_model(dirname, feeded_var_names, target_vars,
                         executor, main_program=None,
                         model_filename=None, params_filename=None):
    """Era-FORMAT save_inference_model: writes the reference's on-disk
    layout (__model__ ProgramDesc protobuf + one save_op-stream file per
    param), so reference-era deployments — and this framework's own
    load_reference_model — can serve a model trained here. The native
    round-trip format is save_inference_model; this is the migration
    EXIT path matching load_reference_model's entry path."""
    from . import reference_format as _rf
    return _rf.save_reference_inference_model(
        dirname, feeded_var_names, target_vars, executor,
        main_program=main_program, model_filename=model_filename,
        params_filename=params_filename)


def load_reference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    """Load a model directory saved by REFERENCE-era code
    (python/paddle/fluid/io.py:384 save_inference_model): a `__model__`
    ProgramDesc protobuf plus one save_op LoDTensor file per persistable
    var. Returns (program, feed_names, fetch_vars) like
    load_inference_model; the program runs on the TPU Executor directly.

    Parsing is a hand-rolled protobuf wire reader
    (paddle_tpu/reference_format.py — framework.proto's schema), so no
    protobuf runtime is needed. params_filename loads the era's
    COMBINED layout (save_combine: all streams in one file, sorted-name
    order — io.py:120/210 sorts on both sides). Sequence models load
    through the
    flat-LoD->padded layout adapter (adapt_sequence_layout). Control-flow
    ops in a LOADED desc (While/conditional_block sub-blocks) are not
    supported: the reference desc carries no loop-carry metadata and the
    era served beam decode from host-side python loops, not saved graphs.
    """
    from . import reference_format as rf

    with open(os.path.join(dirname, model_filename or "__model__"),
              "rb") as f:
        raw = f.read()
    blocks = rf._parse_blocks(raw)  # one wire decode for both consumers
    program = rf.parse_program_desc(blocks)
    feed_names, fetch_names = rf.strip_feed_fetch(blocks)
    # flat-LoD-rows -> padded-dense rewiring (sequence models: lstm/gru/
    # sequence_* ops gain @SEQLEN companions, mul/elementwise gain a rank)
    rf.adapt_sequence_layout(program, feed_names)

    scope = global_scope()
    persistables = [v.name for v in program.list_vars() if v.persistable]
    if params_filename:
        combined = rf.read_combined_lod_tensor_file(
            os.path.join(dirname, params_filename), persistables)
        for name, arr in combined.items():
            scope.set(name, arr)
    else:
        for name in persistables:
            path = os.path.join(dirname, name)
            if not os.path.exists(path):
                raise RuntimeError(
                    "reference model param file missing: %r (a combined "
                    "save needs params_filename=...)" % path)
            arr, _lod = rf.read_lod_tensor_file(path)
            scope.set(name, arr)

    fetch_vars = [program.global_block().var(n) for n in fetch_names]
    return program, feed_names, fetch_vars


def save_checkpoint(executor, checkpoint_dir, main_program=None,
                    trainer_id=0, step=0, max_to_keep=None,
                    keep_every_n_steps=None):
    """Checkpoint save (parity: fluid.io checkpoint utilities).

    Deprecation shim: delegates to `checkpoint.CheckpointManager` with a
    synchronous save, so the legacy one-call API now gets the full
    subsystem — atomic publication (temp dir + fsync + rename; a kill
    mid-save can no longer corrupt the run), per-file content hashes,
    seed-cursor + reader-position capture, and optional retention
    (max_to_keep/keep_every_n_steps; default keeps everything, the legacy
    behavior). Long-running trainers should hold a CheckpointManager
    directly for async saves instead of re-opening one per call."""
    from .checkpoint import CheckpointManager
    mgr = CheckpointManager(checkpoint_dir, max_to_keep=max_to_keep,
                            keep_every_n_steps=keep_every_n_steps,
                            async_save=False)
    try:
        mgr.save(step, program=main_program)
    finally:
        mgr.close()


def load_checkpoint(executor, checkpoint_dir, main_program=None):
    """Checkpoint restore; returns the restored step or None.

    Deprecation shim over `CheckpointManager.restore`: the newest VALID
    snapshot wins — LATEST is only a hint, so a missing/stale pointer or
    a torn/bit-flipped newest save falls back to the newest snapshot
    whose hash tree verifies instead of raising (or worse, resuming from
    garbage). A missing checkpoint dir returns None, like before."""
    from .checkpoint import CheckpointManager
    mgr = CheckpointManager(checkpoint_dir, async_save=False)
    try:
        return mgr.restore(program=main_program, executor=executor)
    finally:
        mgr.close()


def get_parameter_value(para, executor):
    """Current value of a Parameter as numpy (reference io.py:430; here
    values live in the global scope — no fetch program needed)."""
    val = global_scope().get(para.name)
    if val is None:
        raise ValueError("parameter %r not initialized in the current "
                         "scope; run the startup program first" % para.name)
    return np.asarray(val)


def get_parameter_value_by_name(name, executor, program=None):
    """Reference io.py:447: look the Parameter up by name first (raises if
    `name` names a non-parameter variable)."""
    program = program or default_main_program()
    var = program.global_block().var(name)
    if not isinstance(var, Parameter):
        raise TypeError("variable %r is not a Parameter" % name)
    return get_parameter_value(var, executor)
