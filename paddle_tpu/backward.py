"""Alias module (parity: fluid.backward)."""
from .core.backward import append_backward, calc_gradient  # noqa: F401

__all__ = ["append_backward", "calc_gradient"]
