"""Book chapter 05: recommender_system (MovieLens).

Parity: python/paddle/fluid/tests/book/test_recommender_system.py — twin
feature towers (user id/gender/age/job embeddings; movie id embedding +
category sum-pool + title conv-pool), cosine similarity scaled to the
5-star range, squared-error cost.
"""
import paddle_tpu as fluid
from paddle_tpu import nets
from paddle_tpu.datasets import movielens

IS_SPARSE = True

FEED_ORDER = ["user_id", "gender_id", "age_id", "job_id", "movie_id",
              "category_id", "movie_title", "score"]


def get_usr_combined_features(emb_dim=32, fc_dim=200):
    usr_dict_size = movielens.max_user_id() + 1
    uid = fluid.layers.data(name="user_id", shape=[1], dtype="int64")
    usr_emb = fluid.layers.embedding(
        input=uid, dtype="float32", size=[usr_dict_size, emb_dim],
        param_attr="user_table", is_sparse=IS_SPARSE)
    usr_fc = fluid.layers.fc(input=usr_emb, size=emb_dim)

    usr_gender_id = fluid.layers.data(name="gender_id", shape=[1],
                                      dtype="int64")
    usr_gender_emb = fluid.layers.embedding(
        input=usr_gender_id, size=[2, emb_dim // 2],
        param_attr="gender_table", is_sparse=IS_SPARSE)
    usr_gender_fc = fluid.layers.fc(input=usr_gender_emb, size=emb_dim // 2)

    age_size = len(movielens.age_table)
    usr_age_id = fluid.layers.data(name="age_id", shape=[1], dtype="int64")
    usr_age_emb = fluid.layers.embedding(
        input=usr_age_id, size=[age_size, emb_dim // 2],
        is_sparse=IS_SPARSE, param_attr="age_table")
    usr_age_fc = fluid.layers.fc(input=usr_age_emb, size=emb_dim // 2)

    job_size = movielens.max_job_id() + 1
    usr_job_id = fluid.layers.data(name="job_id", shape=[1], dtype="int64")
    usr_job_emb = fluid.layers.embedding(
        input=usr_job_id, size=[job_size, emb_dim // 2],
        param_attr="job_table", is_sparse=IS_SPARSE)
    usr_job_fc = fluid.layers.fc(input=usr_job_emb, size=emb_dim // 2)

    concat_embed = fluid.layers.concat(
        input=[usr_fc, usr_gender_fc, usr_age_fc, usr_job_fc], axis=1)
    return fluid.layers.fc(input=concat_embed, size=fc_dim, act="tanh")


def get_mov_combined_features(emb_dim=32, fc_dim=200):
    mov_dict_size = movielens.max_movie_id() + 1
    mov_id = fluid.layers.data(name="movie_id", shape=[1], dtype="int64")
    mov_emb = fluid.layers.embedding(
        input=mov_id, dtype="float32", size=[mov_dict_size, emb_dim],
        param_attr="movie_table", is_sparse=IS_SPARSE)
    mov_fc = fluid.layers.fc(input=mov_emb, size=emb_dim)

    category_size = len(movielens.movie_categories())
    category_id = fluid.layers.data(
        name="category_id", shape=[1], dtype="int64", lod_level=1)
    mov_categories_emb = fluid.layers.embedding(
        input=category_id, size=[category_size, emb_dim],
        is_sparse=IS_SPARSE)
    mov_categories_hidden = fluid.layers.sequence_pool(
        input=mov_categories_emb, pool_type="sum")

    title_size = len(movielens.get_movie_title_dict())
    mov_title_id = fluid.layers.data(
        name="movie_title", shape=[1], dtype="int64", lod_level=1)
    mov_title_emb = fluid.layers.embedding(
        input=mov_title_id, size=[title_size, emb_dim], is_sparse=IS_SPARSE)
    mov_title_conv = nets.sequence_conv_pool(
        input=mov_title_emb, num_filters=emb_dim, filter_size=3, act="tanh",
        pool_type="sum")

    concat_embed = fluid.layers.concat(
        input=[mov_fc, mov_categories_hidden, mov_title_conv], axis=1)
    return fluid.layers.fc(input=concat_embed, size=fc_dim, act="tanh")


def model(emb_dim=32, fc_dim=200):
    usr_combined_features = get_usr_combined_features(emb_dim, fc_dim)
    mov_combined_features = get_mov_combined_features(emb_dim, fc_dim)

    inference = fluid.layers.cos_sim(X=usr_combined_features,
                                     Y=mov_combined_features)
    scale_infer = fluid.layers.scale(x=inference, scale=5.0)

    label = fluid.layers.data(name="score", shape=[1], dtype="float32")
    square_cost = fluid.layers.square_error_cost(input=scale_infer,
                                                 label=label)
    avg_cost = fluid.layers.mean(x=square_cost)
    return scale_infer, avg_cost


def build_train(learning_rate=0.2, emb_dim=32, fc_dim=200):
    scale_infer, avg_cost = model(emb_dim, fc_dim)
    fluid.optimizer.SGD(learning_rate=learning_rate).minimize(avg_cost)
    return scale_infer, avg_cost
