"""PTB RNN language model (rnnlm).

Parity: the era's RNN-LM benchmark (reference `benchmark/paddle/rnn/rnn_v2.py`
stacked-LSTM LM; SURVEY §2 model list "rnnlm / language_model (ptb)") fed by
`paddle.v2.dataset.imikolov` with ``DataType.SEQ`` shifted (src, trg) pairs
(reference `python/paddle/v2/dataset/imikolov.py:92`).

TPU-first notes: each dynamic_lstm is one masked `lax.scan` whose fused gate
matmul rides the MXU; the tied softmax is a single [B,T,E] x [E,V] batched
matmul against the transposed embedding table (weight tying halves the LM's
parameter count — the table is read by the lookup AND the output projection,
which the vjp-based backward accumulates into one gradient with no extra
plumbing). Loss is the length-masked mean token NLL; perplexity = exp(nll)
is computed in-graph so the fetch is a single scalar.
"""
import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu import ParamAttr
from .common import masked_mean_cost

__all__ = ["build"]


def build(vocab_size=2075, emb_size=64, hidden_size=64, num_layers=2,
          learning_rate=0.003, tie_weights=True, dropout_prob=0.0,
          is_test=False):
    """Stacked-LSTM LM over shifted sequences.

    Feeds: ``words`` / ``nextwords`` — both int64 lod_level=1 sequences
    (imikolov SEQ pairs). Returns (words, nextwords, avg_cost, ppl) where
    avg_cost is mean per-token NLL and ppl its exponent.
    """
    words = layers.data(name="words", shape=[1], dtype="int64", lod_level=1)
    nextwords = layers.data(name="nextwords", shape=[1], dtype="int64",
                            lod_level=1)

    emb = layers.embedding(
        input=words, size=[vocab_size, emb_size], dtype="float32",
        param_attr=ParamAttr(name="lm_embedding"))          # [B,T,E]

    x = emb
    for i in range(num_layers):
        proj = layers.fc(input=x, size=hidden_size * 4,
                         param_attr=ParamAttr(name="lm_lstm_w_%d" % i),
                         bias_attr=ParamAttr(name="lm_lstm_b_%d" % i))
        hidden, _cell = layers.dynamic_lstm(input=proj, size=hidden_size * 4)
        if dropout_prob and not is_test:
            hidden = layers.dropout(hidden, dropout_prob=dropout_prob)
        x = hidden                                          # [B,T,H]

    if tie_weights:
        # project back to embedding width, then logits against the table
        out = layers.fc(input=x, size=emb_size, num_flatten_dims=2,
                        param_attr=ParamAttr(name="lm_proj_w"),
                        bias_attr=ParamAttr(name="lm_proj_b"))  # [B,T,E]
        emb_table = words.block.program.global_block().var("lm_embedding")
        logits = layers.matmul(out, emb_table, transpose_y=True)  # [B,T,V]
        out_bias = layers.create_parameter(
            shape=[vocab_size], dtype="float32", name="lm_out_bias",
            default_initializer=fluid.initializer.Constant(0.0))
        logits = layers.elementwise_add(x=logits, y=out_bias)
    else:
        logits = layers.fc(input=x, size=vocab_size, num_flatten_dims=2,
                           param_attr=ParamAttr(name="lm_softmax_w"),
                           bias_attr=ParamAttr(name="lm_softmax_b"))

    cost = layers.softmax_with_cross_entropy(
        logits=logits, label=nextwords)                     # [B,T,1]
    avg_cost = masked_mean_cost(cost, nextwords, logits)
    ppl = layers.exp(avg_cost)

    if not is_test:
        fluid.optimizer.Adam(learning_rate=learning_rate).minimize(avg_cost)
    return words, nextwords, avg_cost, ppl
