"""Small-config build registry over every bundled model family.

One place that knows how to construct a representative (tiny) training
program per model in `paddle_tpu/models` — the shared work-list of
`tools/pplint.py --all-models` (the tier-1 lint sweep: every bundled
model analyzed under every applicable deployment context) and of the
tooling tests. Configs are deliberately minimal: the SHAPE of each
program (op vocabulary, sub-blocks, sequence plumbing) is what the
consumers exercise, not its capacity.

    for name in zoo.names():
        main, startup = zoo.build(name)
"""
import paddle_tpu as fluid


def _builders():
    L = fluid.layers

    def mnist():
        from . import recognize_digits
        recognize_digits.build(nn_type="conv")

    def sentiment():
        from .understand_sentiment import stacked_lstm_net
        data = L.data(name="words", shape=[1], dtype="int64", lod_level=1)
        stacked_lstm_net(data, dict_dim=100, class_dim=2, emb_dim=16,
                         hid_dim=16, stacked_num=3)

    def seq2seq():
        from .machine_translation import build_train
        build_train(dict_size=30, word_dim=8, hidden_dim=16,
                    decoder_size=16)

    def transformer():
        from . import transformer as tfm
        tfm.build_train(src_vocab_size=20, trg_vocab_size=20, max_length=8,
                        n_layer=1, n_head=2, d_key=8, d_value=8, d_model=16,
                        d_inner_hid=32)

    def srl():
        from . import label_semantic_roles
        label_semantic_roles.build_train(
            word_dict_len=50, label_dict_len=9, pred_dict_len=20,
            word_dim=8, mark_dim=4, hidden_dim=16, depth=2, lr=0.03,
            mix_hidden_lr=1.0)

    def ctr():
        from . import ctr as m
        m.build(sparse_feature_dim=1000, embedding_size=8)

    def word2vec():
        from . import word2vec as m
        m.build(dict_size=100, embed_size=8, hidden_size=16)

    def recommender():
        from . import recommender_system as m
        m.build_train(emb_dim=8, fc_dim=16)

    def language_model():
        from . import language_model as m
        m.build(vocab_size=120, emb_size=8, hidden_size=8, num_layers=2)

    return {"mnist": mnist, "sentiment": sentiment, "seq2seq": seq2seq,
            "transformer": transformer, "srl": srl, "ctr": ctr,
            "word2vec": word2vec, "recommender": recommender,
            "language_model": language_model}


def names():
    """Sorted model names in the zoo."""
    return sorted(_builders())


def build(name):
    """Construct model `name` at its zoo config -> (main, startup)
    Programs, built under fresh name/program guards."""
    builder = _builders().get(name)
    if builder is None:
        raise KeyError("no zoo model named %r (have: %s)"
                       % (name, ", ".join(names())))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        builder()
    return main, startup
