"""Shared model-building helpers."""
from paddle_tpu import layers

__all__ = ["masked_mean_cost"]


def masked_mean_cost(cost, seq_var, maxlen_ref):
    """Length-masked mean of a per-timestep cost over true tokens.

    cost: [B, T, 1] per-position loss (e.g. cross_entropy over a padded
    sequence). seq_var: the sequence data Variable whose lengths companion
    gives each row's true length. maxlen_ref: a [B, T, ...] Variable whose
    time dim sets the mask width. This is the flat-LoD mean of the
    reference era (sum over real tokens / token count) — padding positions
    contribute nothing.
    """
    seq_len = seq_var.block.var_recursive(seq_var.seq_len_var)
    mask = layers.sequence_mask(seq_len, maxlen=maxlen_ref, dtype="float32")
    masked = layers.elementwise_mul(x=layers.squeeze(x=cost, axes=[2]),
                                    y=mask)
    return layers.elementwise_div(
        x=layers.reduce_sum(masked), y=layers.reduce_sum(mask))
