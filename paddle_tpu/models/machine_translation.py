"""Book chapter 08: machine translation (seq2seq, attention, beam search).

Parity: python/paddle/fluid/tests/book/test_machine_translation.py (simple
encoder-decoder + While-loop beam-search decode) and
benchmark/fluid/machine_translation.py (attention seq2seq).

TPU-first notes: the training decoder is a DynamicRNN -> one masked lax.scan;
attention is batched matmul on the MXU with a length-masked softmax; the
decode path is a While loop (lax.while_loop) over dense [batch, beam] state
with the beam_search/beam_search_decode ops (ops/control_ops.py) — no
host-side LoD candidate lists.
"""
import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu import ParamAttr
from .common import masked_mean_cost


def encoder(dict_size, word_dim=16, hidden_dim=32, is_sparse=False):
    """Returns (enc_seq [B,Ts,H] sequence var, enc_last [B,H])."""
    src_word_id = layers.data(
        name="src_word_id", shape=[1], dtype="int64", lod_level=1)
    src_embedding = layers.embedding(
        input=src_word_id, size=[dict_size, word_dim], dtype="float32",
        is_sparse=is_sparse, param_attr=ParamAttr(name="vemb"))
    fc1 = layers.fc(input=src_embedding, size=hidden_dim * 4, act="tanh")
    lstm_hidden0, lstm_0 = layers.dynamic_lstm(
        input=fc1, size=hidden_dim * 4)
    encoder_out = layers.sequence_last_step(input=lstm_hidden0)
    return lstm_hidden0, encoder_out


def _attention(enc_seq, dec_state):
    """Dot-product attention: enc_seq [B,Ts,H] x dec_state [B,H] -> ctx [B,H].

    Scores are masked past each row's true source length via
    sequence_softmax (enc_seq carries its lengths companion).

    Deliberately NOT routed through layers.fused_attention: this runs one
    single-query step inside a DynamicRNN trace, so there is no [T, T]
    score matrix to keep out of HBM — the flash kernel's win — and a
    pallas call per loop step would serialize against the lax.scan. The
    multi-head [B, T, H, D] fused path lives in models/transformer.py."""
    scores = layers.matmul(enc_seq,
                           layers.unsqueeze(x=dec_state, axes=[2]))  # [B,Ts,1]
    scores = layers.squeeze(x=scores, axes=[2])                      # [B,Ts]
    att = layers.sequence_softmax(scores)
    ctx = layers.matmul(layers.unsqueeze(x=att, axes=[1]), enc_seq)  # [B,1,H]
    return layers.squeeze(x=ctx, axes=[1])


def decoder_train(context, enc_seq, dict_size, word_dim=16, decoder_size=32,
                  is_sparse=False, use_attention=False):
    """Teacher-forced decoder. `context` = encoder last state [B,H]."""
    trg_language_word = layers.data(
        name="target_language_word", shape=[1], dtype="int64", lod_level=1)
    trg_embedding = layers.embedding(
        input=trg_language_word, size=[dict_size, word_dim], dtype="float32",
        is_sparse=is_sparse, param_attr=ParamAttr(name="vemb"))

    rnn = layers.DynamicRNN()
    with rnn.block():
        current_word = rnn.step_input(trg_embedding)
        pre_state = rnn.memory(init=context)
        if use_attention:
            ctx = _attention(enc_seq, pre_state)
            fc_in = [current_word, pre_state, ctx]
        else:
            fc_in = [current_word, pre_state]
        current_state = layers.fc(
            input=fc_in, size=decoder_size, act="tanh",
            param_attr=[ParamAttr(name="dec_state_w_%d" % i)
                        for i in range(len(fc_in))],
            bias_attr=ParamAttr(name="dec_state_b"))
        current_score = layers.fc(
            input=current_state, size=dict_size, act="softmax",
            param_attr=ParamAttr(name="dec_score_w"),
            bias_attr=ParamAttr(name="dec_score_b"))
        rnn.update_memory(pre_state, current_state)
        rnn.output(current_score)

    return rnn()


def decoder_decode(context, enc_seq, dict_size, word_dim=16, decoder_size=32,
                   beam_size=2, max_length=8, start_id=1, end_id=2,
                   is_sparse=False, use_attention=False):
    """While-loop beam-search decode, dense [batch, beam] layout.

    Parity: test_machine_translation.py decoder_decode. Weights are shared
    with decoder_train via ParamAttr names. Feed init_ids [B,K] (start_id)
    and init_scores [B,K] ([0, -1e9, ...] per row — see layers.beam_search).
    """
    init_ids = layers.data(name="init_ids", shape=[beam_size],
                           dtype="int64")
    init_scores = layers.data(name="init_scores", shape=[beam_size],
                              dtype="float32")

    counter = layers.zeros(shape=[1], dtype="int32")
    counter.stop_gradient = True
    array_len = layers.fill_constant(shape=[1], dtype="int32",
                                     value=max_length)

    # per-beam decoder state [B, K, H]
    init_state = layers.expand(
        layers.unsqueeze(x=context, axes=[1]), [1, beam_size, 1])
    state_array = layers.create_array("float32", capacity=max_length + 1)
    layers.array_write(init_state, counter, state_array)
    ids_array = layers.create_array("int64", capacity=max_length + 1)
    scores_array = layers.create_array("float32", capacity=max_length + 1)
    parent_array = layers.create_array("int32", capacity=max_length + 1)
    layers.array_write(init_ids, counter, ids_array)
    layers.array_write(init_scores, counter, scores_array)
    init_parent = layers.fill_constant_batch_size_like(
        input=init_ids, shape=[-1, beam_size], dtype="int32", value=0)
    layers.array_write(init_parent, counter, parent_array)

    cond = layers.less_than(x=counter, y=array_len)
    while_op = layers.While(cond=cond)
    with while_op.block():
        pre_ids = layers.array_read(ids_array, counter)       # [B,K] int64
        pre_state = layers.array_read(state_array, counter)   # [B,K,H]
        pre_score = layers.array_read(scores_array, counter)  # [B,K]

        pre_ids_emb = layers.embedding(
            input=pre_ids, size=[dict_size, word_dim], dtype="float32",
            is_sparse=is_sparse, param_attr=ParamAttr(name="vemb"))  # [B,K,E]

        if use_attention:
            # scores over source: [B,K,H] x [B,H,Ts] -> [B,K,Ts], masked
            att_scores = layers.matmul(
                pre_state, layers.transpose(enc_seq, perm=[0, 2, 1]))
            enc_len = enc_seq.block.var_recursive(enc_seq.seq_len_var)
            src_mask = layers.sequence_mask(
                enc_len, maxlen=enc_seq, dtype="float32")     # [B,Ts]
            neg = layers.scale(x=src_mask, scale=1e9, bias=-1e9)
            att_scores = layers.elementwise_add(
                x=att_scores, y=layers.unsqueeze(x=neg, axes=[1]))
            att = layers.softmax(att_scores)                  # [B,K,Ts]
            ctx = layers.matmul(att, enc_seq)                 # [B,K,H]
            fc_in = [pre_ids_emb, pre_state, ctx]
        else:
            fc_in = [pre_ids_emb, pre_state]

        current_state = layers.fc(
            input=fc_in, size=decoder_size, act="tanh", num_flatten_dims=2,
            param_attr=[ParamAttr(name="dec_state_w_%d" % i)
                        for i in range(len(fc_in))],
            bias_attr=ParamAttr(name="dec_state_b"))          # [B,K,H]
        current_logp = layers.fc(
            input=current_state, size=dict_size, num_flatten_dims=2,
            param_attr=ParamAttr(name="dec_score_w"),
            bias_attr=ParamAttr(name="dec_score_b"))          # [B,K,V]
        current_logp = layers.log(layers.softmax(current_logp))

        selected_ids, selected_scores, parent = layers.beam_search(
            pre_ids=pre_ids, pre_scores=pre_score, ids=None,
            scores=current_logp, beam_size=beam_size, end_id=end_id,
            return_parent_idx=True)

        # reorder per-beam state to follow the selected beams:
        # state[b,k] = current_state[b, parent[b,k]]
        onehot = layers.one_hot(parent, beam_size)            # [B,K,Ksrc]
        new_state = layers.matmul(onehot, current_state)      # [B,K,H]

        layers.increment(counter, 1, in_place=True)
        layers.array_write(new_state, counter, state_array)
        layers.array_write(selected_ids, counter, ids_array)
        layers.array_write(selected_scores, counter, scores_array)
        layers.array_write(parent, counter, parent_array)
        layers.less_than(x=counter, y=array_len, cond=cond)

    translation_ids, translation_scores = layers.beam_search_decode(
        ids_array, scores_array, parent_idx=parent_array, end_id=end_id)
    return translation_ids, translation_scores


def build_train(dict_size=100, word_dim=16, hidden_dim=32, decoder_size=32,
                learning_rate=0.01, is_sparse=False, use_attention=False,
                optimizer="adagrad"):
    """Full training graph. Returns (avg_cost, prediction)."""
    enc_seq, context = encoder(dict_size, word_dim, hidden_dim, is_sparse)
    rnn_out = decoder_train(context, enc_seq, dict_size, word_dim,
                            decoder_size, is_sparse, use_attention)
    label = layers.data(name="target_language_next_word", shape=[1],
                        dtype="int64", lod_level=1)
    cost = layers.cross_entropy(input=rnn_out, label=label)  # [B,T,1]
    # masked mean over true target tokens (the reference's flat-LoD mean)
    avg_cost = masked_mean_cost(cost, label, rnn_out)
    opt = (fluid.optimizer.Adam if optimizer == "adam"
           else fluid.optimizer.Adagrad)(learning_rate=learning_rate)
    opt.minimize(avg_cost)
    return avg_cost, rnn_out


def build_decode(dict_size=100, word_dim=16, hidden_dim=32, decoder_size=32,
                 beam_size=2, max_length=8, start_id=1, end_id=2,
                 is_sparse=False, use_attention=False):
    enc_seq, context = encoder(dict_size, word_dim, hidden_dim, is_sparse)
    return decoder_decode(context, enc_seq, dict_size, word_dim, decoder_size,
                          beam_size, max_length, start_id, end_id, is_sparse,
                          use_attention)
