"""Book chapter 02: recognize_digits (MNIST).

Parity: python/paddle/fluid/tests/book/test_recognize_digits.py — same three
network bodies (softmax_regression, multilayer perceptron, LeNet-5-style
conv-pool net) and the same train program shape.
"""
import paddle_tpu as fluid


def softmax_regression(img):
    return fluid.layers.fc(input=img, size=10, act="softmax")


def multilayer_perceptron(img):
    hidden = fluid.layers.fc(input=img, size=128, act="relu")
    hidden = fluid.layers.fc(input=hidden, size=64, act="relu")
    return fluid.layers.fc(input=hidden, size=10, act="softmax")


def convolutional_neural_network(img):
    conv_pool_1 = fluid.nets.simple_img_conv_pool(
        input=img, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act="relu")
    conv_pool_2 = fluid.nets.simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act="relu")
    return fluid.layers.fc(input=conv_pool_2, size=10, act="softmax")


def build(nn_type="conv", with_optimizer=True, learning_rate=0.001):
    """Build the train graph into the current default programs.

    Returns (img, label, avg_loss, acc).
    """
    img = fluid.layers.data(name="img", shape=[1, 28, 28], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    if nn_type == "conv":
        prediction = convolutional_neural_network(img)
    elif nn_type == "mlp":
        prediction = multilayer_perceptron(img)
    else:
        prediction = softmax_regression(img)
    loss = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_loss = fluid.layers.mean(x=loss)
    acc = fluid.layers.accuracy(input=prediction, label=label)
    if with_optimizer:
        optimizer = fluid.optimizer.Adam(learning_rate=learning_rate)
        optimizer.minimize(avg_loss)
    return img, label, avg_loss, acc
