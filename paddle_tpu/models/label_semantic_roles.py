"""Book chapter 07: label_semantic_roles (CoNLL-05 SRL).

Parity: python/paddle/fluid/tests/book/test_label_semantic_roles.py —
the db-lstm topology (8 feature embeddings, depth-8 stack of alternating
forward/reverse LSTMs with direct edges) into a linear-chain CRF cost,
Viterbi decode for inference.
"""
import paddle_tpu as fluid

FEATURE_NAMES = ["word_data", "ctx_n2_data", "ctx_n1_data", "ctx_0_data",
                 "ctx_p1_data", "ctx_p2_data", "verb_data", "mark_data"]


def db_lstm(word, predicate, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, mark,
            word_dict_len, label_dict_len, pred_dict_len, word_dim=32,
            mark_dim=5, mark_dict_len=2, hidden_dim=512, depth=8,
            is_sparse=True, embedding_name="emb"):
    predicate_embedding = fluid.layers.embedding(
        input=predicate, size=[pred_dict_len, word_dim], dtype="float32",
        is_sparse=is_sparse, param_attr="vemb")
    mark_embedding = fluid.layers.embedding(
        input=mark, size=[mark_dict_len, mark_dim], dtype="float32",
        is_sparse=is_sparse)

    word_input = [word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2]
    emb_layers = [
        fluid.layers.embedding(
            size=[word_dict_len, word_dim], input=x,
            param_attr=fluid.ParamAttr(name=embedding_name, trainable=False))
        for x in word_input
    ]
    emb_layers.append(predicate_embedding)
    emb_layers.append(mark_embedding)

    hidden_0_layers = [
        fluid.layers.fc(input=emb, size=hidden_dim) for emb in emb_layers
    ]
    hidden_0 = fluid.layers.sums(input=hidden_0_layers)

    lstm_0, _ = fluid.layers.dynamic_lstm(
        input=hidden_0, size=hidden_dim, candidate_activation="relu",
        gate_activation="sigmoid", cell_activation="sigmoid")

    # stack L-LSTM and R-LSTM with direct edges
    input_tmp = [hidden_0, lstm_0]
    for i in range(1, depth):
        mix_hidden = fluid.layers.sums(input=[
            fluid.layers.fc(input=input_tmp[0], size=hidden_dim),
            fluid.layers.fc(input=input_tmp[1], size=hidden_dim)
        ])
        lstm, _ = fluid.layers.dynamic_lstm(
            input=mix_hidden, size=hidden_dim, candidate_activation="relu",
            gate_activation="sigmoid", cell_activation="sigmoid",
            is_reverse=((i % 2) == 1))
        input_tmp = [mix_hidden, lstm]

    feature_out = fluid.layers.sums(input=[
        fluid.layers.fc(input=input_tmp[0], size=label_dict_len),
        fluid.layers.fc(input=input_tmp[1], size=label_dict_len)
    ])
    return feature_out


def build_train(word_dict_len, label_dict_len, pred_dict_len,
                mix_hidden_lr=1e-3, lr=0.01, **model_kwargs):
    """Declare data layers, db_lstm, CRF cost + decode + chunk counts.

    Returns (feed_names, avg_cost, crf_decode, chunk_counts).
    """
    feats = {}
    for name in FEATURE_NAMES:
        feats[name] = fluid.layers.data(
            name=name, shape=[1], dtype="int64", lod_level=1)
    target = fluid.layers.data(
        name="target", shape=[1], dtype="int64", lod_level=1)

    feature_out = db_lstm(
        word=feats["word_data"], predicate=feats["verb_data"],
        ctx_n2=feats["ctx_n2_data"], ctx_n1=feats["ctx_n1_data"],
        ctx_0=feats["ctx_0_data"], ctx_p1=feats["ctx_p1_data"],
        ctx_p2=feats["ctx_p2_data"], mark=feats["mark_data"],
        word_dict_len=word_dict_len, label_dict_len=label_dict_len,
        pred_dict_len=pred_dict_len, **model_kwargs)

    crf_cost = fluid.layers.linear_chain_crf(
        input=feature_out, label=target,
        param_attr=fluid.ParamAttr(name="crfw", learning_rate=mix_hidden_lr))
    avg_cost = fluid.layers.mean(x=crf_cost)

    sgd_optimizer = fluid.optimizer.SGD(
        learning_rate=fluid.layers.exponential_decay(
            learning_rate=lr, decay_steps=100000, decay_rate=0.5,
            staircase=True))
    sgd_optimizer.minimize(avg_cost)

    crf_decode = fluid.layers.crf_decoding(
        input=feature_out, param_attr=fluid.ParamAttr(name="crfw"))
    import math
    chunk_counts = fluid.layers.chunk_eval(
        input=crf_decode, label=target, chunk_scheme="IOB",
        num_chunk_types=int(math.ceil((label_dict_len - 1) / 2.0)))

    feed_names = FEATURE_NAMES + ["target"]
    return feed_names, avg_cost, crf_decode, chunk_counts
