"""Book chapter 06: understand_sentiment (IMDB).

Parity: python/paddle/fluid/tests/book/test_understand_sentiment.py —
conv net (sequence_conv_pool) and stacked bi-LSTM bodies.
"""
import paddle_tpu as fluid


def convolution_net(data, dict_dim, class_dim=2, emb_dim=32, hid_dim=32):
    emb = fluid.layers.embedding(input=data, size=[dict_dim, emb_dim])
    conv_3 = fluid.nets.sequence_conv_pool(
        input=emb, num_filters=hid_dim, filter_size=3, act="tanh",
        pool_type="sqrt")
    conv_4 = fluid.nets.sequence_conv_pool(
        input=emb, num_filters=hid_dim, filter_size=4, act="tanh",
        pool_type="sqrt")
    return fluid.layers.fc(input=[conv_3, conv_4], size=class_dim,
                           act="softmax")


def stacked_lstm_net(data, dict_dim, class_dim=2, emb_dim=128, hid_dim=512,
                     stacked_num=3):
    assert stacked_num % 2 == 1
    emb = fluid.layers.embedding(input=data, size=[dict_dim, emb_dim])
    fc1 = fluid.layers.fc(input=emb, size=hid_dim)
    lstm1, cell1 = fluid.layers.dynamic_lstm(input=fc1, size=hid_dim)
    inputs = [fc1, lstm1]
    for i in range(2, stacked_num + 1):
        fc = fluid.layers.fc(input=inputs, size=hid_dim)
        lstm, cell = fluid.layers.dynamic_lstm(
            input=fc, size=hid_dim, is_reverse=(i % 2) == 0)
        inputs = [fc, lstm]
    fc_last = fluid.layers.sequence_pool(input=inputs[0], pool_type="max")
    lstm_last = fluid.layers.sequence_pool(input=inputs[1], pool_type="max")
    return fluid.layers.fc(input=[fc_last, lstm_last], size=class_dim,
                           act="softmax")


def build(net="lstm", dict_dim=1000, class_dim=2, learning_rate=0.002,
          emb_dim=32, hid_dim=32):
    data = fluid.layers.data(name="words", shape=[1], dtype="int64",
                             lod_level=1)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    if net == "conv":
        prediction = convolution_net(data, dict_dim, class_dim, emb_dim,
                                     hid_dim)
    else:
        prediction = stacked_lstm_net(data, dict_dim, class_dim, emb_dim,
                                      hid_dim, stacked_num=3)
    cost = fluid.layers.cross_entropy(input=prediction, label=label)
    avg_cost = fluid.layers.mean(x=cost)
    acc = fluid.layers.accuracy(input=prediction, label=label)
    fluid.optimizer.Adam(learning_rate=learning_rate).minimize(avg_cost)
    return data, label, avg_cost, acc
