"""Book chapter 04: word2vec N-gram language model (imikolov).

Parity: python/paddle/fluid/tests/book/test_word2vec.py — 4-word context,
shared embedding, concat → hidden fc → softmax.
"""
import paddle_tpu as fluid


def build(dict_size=1000, embed_size=32, hidden_size=256, is_sparse=False,
          learning_rate=0.001):
    words = []
    for name in ("firstw", "secondw", "thirdw", "forthw", "nextw"):
        words.append(fluid.layers.data(name=name, shape=[1], dtype="int64"))

    embs = []
    for w in words[:4]:
        embs.append(fluid.layers.embedding(
            input=w, size=[dict_size, embed_size],
            param_attr=fluid.ParamAttr(name="shared_w"), is_sparse=is_sparse))

    concat_embed = fluid.layers.concat(input=embs, axis=1)
    hidden1 = fluid.layers.fc(input=concat_embed, size=hidden_size,
                              act="sigmoid")
    predict_word = fluid.layers.fc(input=hidden1, size=dict_size,
                                   act="softmax")
    cost = fluid.layers.cross_entropy(input=predict_word, label=words[4])
    avg_cost = fluid.layers.mean(x=cost)
    fluid.optimizer.SGD(learning_rate=learning_rate).minimize(avg_cost)
    return words, avg_cost
