"""CTR (click-through-rate) model: wide & deep over sparse id features.

Parity: the reference's CTR workload (paddle/v2 CTR demo; fluid-era dist
CTR benchmark) — per-slot sparse embeddings + dense features, deep MLP tower
plus a wide (logistic) part, log-loss. The pserver story there shards the big
embedding tables across servers; here `embedding_param_names()` hands the
table names to DistributeTranspiler.parameter_shardings / ParallelExecutor
so the tables shard dim-0 over the mesh and lookups become GSPMD gathers
over ICI (the `is_sparse=True` SelectedRows path is a no-op on TPU: XLA
gathers/scatter-adds are already sparse-efficient).
"""
import paddle_tpu as fluid

DENSE_DIM = 13
NUM_SLOTS = 26


def build(sparse_feature_dim=100000, embedding_size=16, dense_dim=DENSE_DIM,
          num_slots=NUM_SLOTS, hidden_sizes=(400, 400, 400),
          learning_rate=1e-3, is_sparse=True, with_optimizer=True):
    """Returns (feeds, avg_cost, predict). Feeds: dense, C0..Cn-1, label."""
    dense = fluid.layers.data(name="dense_input", shape=[dense_dim],
                              dtype="float32")
    sparse_ins = [fluid.layers.data(name="C%d" % i, shape=[1], dtype="int64")
                  for i in range(num_slots)]
    label = fluid.layers.data(name="label", shape=[1], dtype="float32")

    # deep tower: per-slot embeddings + dense features
    embs = [fluid.layers.embedding(
        input=s, size=[sparse_feature_dim, embedding_size],
        is_sparse=is_sparse,
        param_attr=fluid.ParamAttr(name="emb_slot_%d" % i))
        for i, s in enumerate(sparse_ins)]
    deep = fluid.layers.concat(input=embs + [dense], axis=1)
    for i, h in enumerate(hidden_sizes):
        deep = fluid.layers.fc(input=deep, size=h, act="relu")
    deep_logit = fluid.layers.fc(input=deep, size=1)

    # wide part: one scalar weight per sparse id (embedding_size=1) + dense lr
    wide_embs = [fluid.layers.embedding(
        input=s, size=[sparse_feature_dim, 1], is_sparse=is_sparse,
        param_attr=fluid.ParamAttr(name="wide_slot_%d" % i))
        for i, s in enumerate(sparse_ins)]
    wide_logit = fluid.layers.sums(
        [fluid.layers.fc(input=dense, size=1)] + wide_embs)

    logit = fluid.layers.elementwise_add(deep_logit, wide_logit)
    predict = fluid.layers.sigmoid(logit)
    cost = fluid.layers.sigmoid_cross_entropy_with_logits(x=logit,
                                                          label=label)
    avg_cost = fluid.layers.mean(x=cost)
    if with_optimizer:
        fluid.optimizer.Adam(learning_rate=learning_rate).minimize(avg_cost)
    feeds = [dense] + sparse_ins + [label]
    return feeds, avg_cost, predict


def embedding_param_names(num_slots=NUM_SLOTS):
    """The big tables to shard over the mesh (pserver-equivalent placement)."""
    return ["emb_slot_%d" % i for i in range(num_slots)] + \
           ["wide_slot_%d" % i for i in range(num_slots)]
