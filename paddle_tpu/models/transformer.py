"""Transformer (Attention is All You Need) built from fluid layers.

Parity: the fluid benchmark transformer family SURVEY.md §2 lists
("transformer & OCR-CTC"); same program structure as Paddle's
models/transformer: multi_head_attention / positionwise_feed_forward /
pre_post_process_layer helpers, sinusoid position encoding as a frozen
embedding table, attention-bias feeds for padding/causal masks, label
smoothing + per-token weighted cross entropy, Adam + noam warmup.

TPU notes: the whole model is dense [batch, max_len, d_model] with masks
carried as additive bias tensors — no dynamic shapes anywhere, so the
single jitted program covers every batch; attention matmuls land on the
MXU in one fused XLA graph.
"""
import numpy as np

import paddle_tpu as fluid

POS_ENC_PARAM_NAMES = ("src_pos_enc_table", "trg_pos_enc_table")


def position_encoding_init(n_position, d_model):
    """Sinusoid table [n_position, d_model]."""
    pos = np.arange(n_position)[:, None].astype("float64")
    dim = np.arange(d_model)[None, :].astype("float64")
    angle = pos / np.power(10000, 2 * (dim // 2) / d_model)
    table = np.zeros((n_position, d_model))
    table[:, 0::2] = np.sin(angle[:, 0::2])
    table[:, 1::2] = np.cos(angle[:, 1::2])
    return table.astype("float32")


def multi_head_attention(queries, keys, values, attn_bias, d_key, d_value,
                         d_model, n_head=1, dropout_rate=0.0,
                         use_fused=False, causal=False, kv_len=None,
                         fuse_qkv=False):
    """q/k/v fc -> split heads -> scaled dot-product + bias -> combine.

    use_fused routes the core through layers.fused_attention (the pallas
    flash kernel, ops/pallas_kernels.py): the [T, T] score matrix never
    hits HBM, padding is expressed as kv_len + causal instead of the dense
    additive attn_bias (which the fused path ignores). Attention-weight
    dropout can't be expressed inside the flash kernel, so
    use_fused + dropout_rate>0 raises (a silent dense fallback would run
    WITHOUT the causal/kv_len masks, leaking future positions).

    fuse_qkv (self-attention only): one [D, (2*d_key+d_value)*H] matmul
    instead of three — a larger MXU tile and one pass over the
    activations. The combined weight is the COLUMN concatenation
    [W_q | W_k | W_v] of the unfused weights (tested equivalent).
    NOTE: the decode builders (build_decode/build_cached_decode) create
    the unfused three-weight layout; a scope trained with fuse_qkv=True
    cannot be decoded by them (they raise if asked)."""
    if use_fused and dropout_rate:
        raise ValueError(
            "use_fused attention requires dropout_rate=0: attention-weight "
            "dropout can't run inside the flash kernel, and the dense path "
            "expresses masks as attn_bias, not causal/kv_len")
    if use_fused and attn_bias is not None:
        raise ValueError(
            "use_fused attention ignores dense attn_bias tensors — express "
            "the mask as kv_len (key padding) and/or causal=True instead")
    if fuse_qkv and keys is not None:
        raise ValueError("fuse_qkv requires self-attention (keys=None): "
                         "cross-attention projects different inputs")
    if fuse_qkv and d_value != d_key:
        raise ValueError(
            "fuse_qkv requires d_value == d_key: a single Xavier init "
            "cannot match both per-slice scales otherwise")
    keys = queries if keys is None else keys
    values = keys if values is None else values

    if fuse_qkv:
        # per-slice Xavier scale: the fused weight's natural fan_out is 3x
        # a single projection's, which would shrink init std vs the
        # unfused path — pin fan_out to one projection so the flag stays a
        # pure perf toggle at default init
        # the distinct param name makes a layout-mismatched decode build
        # fail fast on a missing parameter instead of silently reading a
        # shape-coincident fc weight from the trained scope
        qkv = fluid.layers.fc(
            input=queries, size=(2 * d_key + d_value) * n_head,
            bias_attr=False, num_flatten_dims=2,
            param_attr=fluid.ParamAttr(
                name=fluid.unique_name.generate("fused_qkv.w"),
                initializer=fluid.initializer.XavierInitializer(
                    fan_out=d_key * n_head)))
        q, k, v = fluid.layers.split(
            qkv, num_or_sections=[d_key * n_head, d_key * n_head,
                                  d_value * n_head], dim=-1)
    else:
        q = fluid.layers.fc(input=queries, size=d_key * n_head,
                            bias_attr=False, num_flatten_dims=2)
        k = fluid.layers.fc(input=keys, size=d_key * n_head,
                            bias_attr=False, num_flatten_dims=2)
        v = fluid.layers.fc(input=values, size=d_value * n_head,
                            bias_attr=False, num_flatten_dims=2)

    if use_fused:
        # [B, T, H*d] -> [B, T, H, d] (BTHD, the fused kernel's layout)
        qf = fluid.layers.reshape(q, shape=[0, -1, n_head, d_key])
        kf = fluid.layers.reshape(k, shape=[0, -1, n_head, d_key])
        vf = fluid.layers.reshape(v, shape=[0, -1, n_head, d_value])
        ctx = fluid.layers.fused_attention(qf, kf, vf, causal=causal,
                                           kv_len=kv_len)
        ctx = fluid.layers.reshape(ctx, shape=[0, -1, n_head * d_value])
        return fluid.layers.fc(input=ctx, size=d_model, bias_attr=False,
                               num_flatten_dims=2)

    def split_heads(x, d):
        # [B, T, H*d] -> [B, H, T, d]
        reshaped = fluid.layers.reshape(x, shape=[0, -1, n_head, d])
        return fluid.layers.transpose(reshaped, perm=[0, 2, 1, 3])

    q = split_heads(q, d_key)
    k = split_heads(k, d_key)
    v = split_heads(v, d_value)

    product = fluid.layers.matmul(x=q, y=k, transpose_y=True)
    product = fluid.layers.scale(x=product, scale=d_key ** -0.5)
    if attn_bias is not None:
        product = product + attn_bias
    weights = fluid.layers.softmax(product)
    if dropout_rate:
        weights = fluid.layers.dropout(weights, dropout_prob=dropout_rate)
    ctx = fluid.layers.matmul(weights, v)              # [B, H, T, dv]
    ctx = fluid.layers.transpose(ctx, perm=[0, 2, 1, 3])
    ctx = fluid.layers.reshape(ctx, shape=[0, -1, n_head * d_value])
    return fluid.layers.fc(input=ctx, size=d_model, bias_attr=False,
                           num_flatten_dims=2)


def positionwise_feed_forward(x, d_inner_hid, d_model):
    hidden = fluid.layers.fc(input=x, size=d_inner_hid, num_flatten_dims=2,
                             act="relu")
    return fluid.layers.fc(input=hidden, size=d_model, num_flatten_dims=2)


def pre_post_process_layer(prev_out, out, process_cmd, dropout_rate=0.0):
    """'a': residual add, 'n': layer_norm, 'd': dropout."""
    for cmd in process_cmd:
        if cmd == "a":
            out = out + prev_out if prev_out is not None else out
        elif cmd == "n":
            out = fluid.layers.layer_norm(
                out, begin_norm_axis=len(out.shape) - 1,
                param_attr=fluid.initializer.Constant(1.0),
                bias_attr=fluid.initializer.Constant(0.0))
        elif cmd == "d":
            if dropout_rate:
                out = fluid.layers.dropout(out, dropout_prob=dropout_rate)
    return out


def prepare_encoder(src_word, src_pos, src_vocab_size, src_emb_dim,
                    src_max_len, dropout_rate=0.0, pos_enc_param_name=None):
    """word emb * sqrt(d) + frozen sinusoid position emb."""
    word_emb = fluid.layers.embedding(
        src_word, size=[src_vocab_size, src_emb_dim],
        param_attr=fluid.ParamAttr(
            initializer=fluid.initializer.Normal(0., src_emb_dim ** -0.5)))
    word_emb = fluid.layers.scale(x=word_emb, scale=src_emb_dim ** 0.5)
    pos_enc = fluid.layers.embedding(
        src_pos, size=[src_max_len, src_emb_dim],
        param_attr=fluid.ParamAttr(
            name=pos_enc_param_name, trainable=False,
            initializer=fluid.initializer.NumpyArrayInitializer(
                position_encoding_init(src_max_len, src_emb_dim))))
    enc_input = word_emb + pos_enc
    if dropout_rate:
        enc_input = fluid.layers.dropout(enc_input,
                                         dropout_prob=dropout_rate)
    return enc_input


def encoder_layer(enc_input, attn_bias, n_head, d_key, d_value, d_model,
                  d_inner_hid, dropout_rate=0.0, use_fused=False,
                  kv_len=None, fuse_qkv=False):
    attn_output = multi_head_attention(
        pre_post_process_layer(None, enc_input, "n"), None, None, attn_bias,
        d_key, d_value, d_model, n_head, dropout_rate,
        use_fused=use_fused, kv_len=kv_len, fuse_qkv=fuse_qkv)
    attn_output = pre_post_process_layer(enc_input, attn_output, "da",
                                         dropout_rate)
    ffd_output = positionwise_feed_forward(
        pre_post_process_layer(None, attn_output, "n"), d_inner_hid, d_model)
    return pre_post_process_layer(attn_output, ffd_output, "da",
                                  dropout_rate)


def decoder_layer(dec_input, enc_output, slf_attn_bias, dec_enc_attn_bias,
                  n_head, d_key, d_value, d_model, d_inner_hid,
                  dropout_rate=0.0, use_fused=False, src_len=None,
                  trg_len=None, fuse_qkv=False):
    slf_attn_output = multi_head_attention(
        pre_post_process_layer(None, dec_input, "n"), None, None,
        slf_attn_bias, d_key, d_value, d_model, n_head, dropout_rate,
        use_fused=use_fused, causal=True, kv_len=trg_len,
        fuse_qkv=fuse_qkv)
    slf_attn_output = pre_post_process_layer(dec_input, slf_attn_output,
                                             "da", dropout_rate)
    enc_attn_output = multi_head_attention(
        pre_post_process_layer(None, slf_attn_output, "n"), enc_output,
        enc_output, dec_enc_attn_bias, d_key, d_value, d_model, n_head,
        dropout_rate, use_fused=use_fused, kv_len=src_len)
    enc_attn_output = pre_post_process_layer(slf_attn_output,
                                             enc_attn_output, "da",
                                             dropout_rate)
    ffd_output = positionwise_feed_forward(
        pre_post_process_layer(None, enc_attn_output, "n"), d_inner_hid,
        d_model)
    return pre_post_process_layer(enc_attn_output, ffd_output, "da",
                                  dropout_rate)


def encoder(enc_input, attn_bias, n_layer, n_head, d_key, d_value, d_model,
            d_inner_hid, dropout_rate=0.0, use_fused=False, kv_len=None,
            fuse_qkv=False):
    for _ in range(n_layer):
        enc_input = encoder_layer(enc_input, attn_bias, n_head, d_key,
                                  d_value, d_model, d_inner_hid,
                                  dropout_rate, use_fused=use_fused, fuse_qkv=fuse_qkv,
                                  kv_len=kv_len)
    return pre_post_process_layer(None, enc_input, "n")


def decoder(dec_input, enc_output, slf_attn_bias, dec_enc_attn_bias,
            n_layer, n_head, d_key, d_value, d_model, d_inner_hid,
            dropout_rate=0.0, use_fused=False, src_len=None, trg_len=None,
            fuse_qkv=False):
    for _ in range(n_layer):
        dec_input = decoder_layer(dec_input, enc_output, slf_attn_bias,
                                  dec_enc_attn_bias, n_head, d_key, d_value,
                                  d_model, d_inner_hid, dropout_rate,
                                  use_fused=use_fused, fuse_qkv=fuse_qkv, src_len=src_len,
                                  trg_len=trg_len)
    return pre_post_process_layer(None, dec_input, "n")


FEED_NAMES = ["src_word", "src_pos", "trg_word", "trg_pos",
              "src_slf_attn_bias", "trg_slf_attn_bias", "trg_src_attn_bias",
              "lbl_word", "lbl_weight"]
FUSED_FEED_NAMES = ["src_word", "src_pos", "trg_word", "trg_pos",
                    "src_len", "trg_len", "lbl_word", "lbl_weight"]


def make_inputs(max_length, n_head, fused=False):
    """Declare the dense feeds. Classic design: 9 feeds with [H, T, T]
    additive attention-bias tensors. fused=True (flash-attention path):
    the three bias tensors are replaced by [B] int32 src_len/trg_len —
    padding becomes kv_len block-skipping instead of O(T^2) -1e9 adds."""
    src_word = fluid.layers.data("src_word", [max_length], dtype="int64")
    src_pos = fluid.layers.data("src_pos", [max_length], dtype="int64")
    trg_word = fluid.layers.data("trg_word", [max_length], dtype="int64")
    trg_pos = fluid.layers.data("trg_pos", [max_length], dtype="int64")
    if fused:
        src_len = fluid.layers.data("src_len", [1], dtype="int32")
        trg_len = fluid.layers.data("trg_len", [1], dtype="int32")
    else:
        src_slf = fluid.layers.data(
            "src_slf_attn_bias", [n_head, max_length, max_length])
        trg_slf = fluid.layers.data(
            "trg_slf_attn_bias", [n_head, max_length, max_length])
        trg_src = fluid.layers.data(
            "trg_src_attn_bias", [n_head, max_length, max_length])
    lbl_word = fluid.layers.data("lbl_word", [max_length, 1], dtype="int64")
    lbl_weight = fluid.layers.data("lbl_weight", [max_length, 1])
    if fused:
        return (src_word, src_pos, trg_word, trg_pos, src_len, trg_len,
                lbl_word, lbl_weight)
    return (src_word, src_pos, trg_word, trg_pos, src_slf, trg_slf, trg_src,
            lbl_word, lbl_weight)


def transformer(src_vocab_size, trg_vocab_size, max_length, n_layer=2,
                n_head=4, d_key=16, d_value=16, d_model=64, d_inner_hid=128,
                dropout_rate=0.0, label_smooth_eps=0.0,
                use_fused_attention=False, use_fused_label_smooth=True,
                use_qkv_fusion=False):
    """Build the training graph; returns (sum_cost, avg_cost, predict).

    use_fused_attention: every attention core runs the pallas flash kernel
    (padding via src_len/trg_len feeds, decoder causality via the kernel's
    causal block-skipping). Requires dropout_rate == 0.

    use_fused_label_smooth: compute uniform label smoothing by exact
    decomposition ((1-eps)*nll + eps*(lse - mean logits)) instead of the
    dense [N, vocab] smoothed-label + soft-softmax path — numerically
    identical; the remaining [N, vocab] intermediates are fusion-friendly
    (one_hot compare + reduce) rather than stored labels."""
    if use_fused_attention:
        if dropout_rate:
            raise ValueError("use_fused_attention requires dropout_rate=0 "
                             "(attention-weight dropout can't run inside "
                             "the flash kernel)")
        (src_word, src_pos, trg_word, trg_pos, src_len, trg_len,
         lbl_word, lbl_weight) = make_inputs(max_length, n_head, fused=True)
        src_slf_attn_bias = trg_slf_attn_bias = trg_src_attn_bias = None
    else:
        (src_word, src_pos, trg_word, trg_pos, src_slf_attn_bias,
         trg_slf_attn_bias, trg_src_attn_bias, lbl_word,
         lbl_weight) = make_inputs(max_length, n_head)
        src_len = trg_len = None

    enc_input = prepare_encoder(
        src_word, src_pos, src_vocab_size, d_model, max_length,
        dropout_rate, pos_enc_param_name=POS_ENC_PARAM_NAMES[0])
    enc_output = encoder(enc_input, src_slf_attn_bias, n_layer, n_head,
                         d_key, d_value, d_model, d_inner_hid, dropout_rate,
                         use_fused=use_fused_attention, kv_len=src_len,
                         fuse_qkv=use_qkv_fusion)

    dec_input = prepare_encoder(
        trg_word, trg_pos, trg_vocab_size, d_model, max_length,
        dropout_rate, pos_enc_param_name=POS_ENC_PARAM_NAMES[1])
    dec_output = decoder(dec_input, enc_output, trg_slf_attn_bias,
                         trg_src_attn_bias, n_layer, n_head, d_key, d_value,
                         d_model, d_inner_hid, dropout_rate,
                         use_fused=use_fused_attention, src_len=src_len,
                         trg_len=trg_len, fuse_qkv=use_qkv_fusion)

    predict = fluid.layers.fc(input=dec_output, size=trg_vocab_size,
                              bias_attr=False, num_flatten_dims=2)
    predict_2d = fluid.layers.reshape(predict, shape=[-1, trg_vocab_size])
    lbl_flat = fluid.layers.reshape(lbl_word, shape=[-1, 1])
    if label_smooth_eps and use_fused_label_smooth:
        # exact decomposition of uniform label smoothing: with
        # lse = logit_label + nll,
        #   -(sum smoothed*logp) = (1-eps)*nll + eps*(lse - sum(logits)/V)
        #                        = nll + eps*(logit_label - sum(logits)/V).
        # Replaces the naive path's [N, V] smoothed-label matrix and
        # soft-label softmax with the hard-label fused pallas xent kernel
        # plus per-row reductions; logit_label still goes through a
        # one_hot*logits reduce whose fusion (no materialized [N, V]
        # buffer) is up to XLA — no gather-by-label layer exists. Gradient
        # (1-eps)*(p - onehot) + eps*(p - 1/V) falls out of the vjp.
        nll = fluid.layers.softmax_with_cross_entropy(
            logits=predict_2d, label=lbl_flat)
        logit_lbl = fluid.layers.reduce_sum(
            fluid.layers.one_hot(lbl_flat, depth=trg_vocab_size)
            * predict_2d, dim=1, keep_dim=True)
        cost = nll + label_smooth_eps * (
            logit_lbl - fluid.layers.reduce_sum(
                predict_2d, dim=1, keep_dim=True) / float(trg_vocab_size))
    elif label_smooth_eps:
        smoothed = fluid.layers.label_smooth(
            fluid.layers.one_hot(lbl_flat, depth=trg_vocab_size),
            epsilon=label_smooth_eps)
        cost = fluid.layers.softmax_with_cross_entropy(
            logits=predict_2d, label=smoothed, soft_label=True)
    else:
        cost = fluid.layers.softmax_with_cross_entropy(
            logits=predict_2d, label=lbl_flat)
    weight_flat = fluid.layers.reshape(lbl_weight, shape=[-1, 1])
    weighted_cost = cost * weight_flat
    sum_cost = fluid.layers.reduce_sum(weighted_cost)
    token_num = fluid.layers.reduce_sum(weight_flat)
    token_num.stop_gradient = True
    avg_cost = sum_cost / token_num
    return sum_cost, avg_cost, predict


def build_train(src_vocab_size, trg_vocab_size, max_length, d_model=64,
                warmup_steps=40, learning_rate=1.0, **kwargs):
    sum_cost, avg_cost, predict = transformer(
        src_vocab_size, trg_vocab_size, max_length, d_model=d_model,
        **kwargs)
    lr = fluid.layers.noam_decay(d_model, warmup_steps, learning_rate)
    optimizer = fluid.optimizer.Adam(learning_rate=lr, beta1=0.9,
                                     beta2=0.98, epsilon=1e-9)
    optimizer.minimize(avg_cost)
    return sum_cost, avg_cost, predict


def build_decode(src_vocab_size, trg_vocab_size, max_length, n_layer=2,
                 n_head=4, d_key=16, d_value=16, d_model=64,
                 d_inner_hid=128, beam_size=2, max_out_len=None,
                 bos_id=1, eos_id=2, fuse_qkv=False):
    """Autoregressive beam-search decode (the era's transformer infer
    path: re-run the whole decoder on the growing prefix each step — no
    KV cache in the reference either; dense [batch, beam] layout rides
    one lax.while_loop like models/machine_translation.decoder_decode).

    Build under a fresh unique_name.guard with the SAME call sequence as
    `transformer`, so every parameter shares its training name and the
    decode program runs in the training scope. Returns
    (sentence_ids [B, K, C], sentence_scores [B, K]).
    """
    if fuse_qkv:
        raise NotImplementedError(
            "the decode builders create the unfused q/k/v weight layout; "
            "decode a fuse_qkv-trained scope is not supported — train "
            "with use_qkv_fusion=False for decode interop")

    L = fluid.layers
    K = beam_size
    T = max_length
    limit_steps = T - 1 if max_out_len is None else min(max_out_len, T - 1)

    src_word = L.data("src_word", [T], dtype="int64")
    src_pos = L.data("src_pos", [T], dtype="int64")
    src_slf = L.data("src_slf_attn_bias", [n_head, T, T])
    trg_pos_full = L.data("trg_pos_full", [T], dtype="int64")
    trg_slf = L.data("trg_slf_attn_bias", [n_head, T, T])
    trg_src = L.data("trg_src_attn_bias", [n_head, T, T])
    init_ids = L.data("init_ids", [K], dtype="int64")
    init_scores = L.data("init_scores", [K])

    # encoder: identical call order to `transformer` => identical param
    # names (word emb, encoder fcs)
    enc_input = prepare_encoder(
        src_word, src_pos, src_vocab_size, d_model, T, 0.0,
        pos_enc_param_name=POS_ENC_PARAM_NAMES[0])
    enc_output = encoder(enc_input, src_slf, n_layer, n_head, d_key,
                         d_value, d_model, d_inner_hid)

    def beam_rep(x, tail_dims):
        """[B, ...] -> [B*K, ...] (repeat each row per beam)."""
        r = L.expand(L.unsqueeze(x, axes=[1]),
                     [1, K] + [1] * len(tail_dims))
        return L.reshape(r, shape=[-1] + list(tail_dims))

    enc_rep = beam_rep(enc_output, [T, d_model])
    trg_slf_rep = beam_rep(trg_slf, [n_head, T, T])
    trg_src_rep = beam_rep(trg_src, [n_head, T, T])
    trg_pos_rep = beam_rep(trg_pos_full, [T])

    counter = L.zeros(shape=[1], dtype="int32")
    counter.stop_gradient = True
    limit = L.fill_constant(shape=[1], dtype="int32", value=limit_steps)

    ids_array = L.create_array("int64", capacity=limit_steps + 1)
    scores_array = L.create_array("float32", capacity=limit_steps + 1)
    parent_array = L.create_array("int32", capacity=limit_steps + 1)
    L.array_write(init_ids, counter, ids_array)
    L.array_write(init_scores, counter, scores_array)
    init_parent = L.fill_constant_batch_size_like(
        input=init_ids, shape=[-1, K], dtype="int32", value=0)
    L.array_write(init_parent, counter, parent_array)

    # the decoded prefix, float-typed so one_hot matmul reordering works;
    # cast to int64 for the embedding lookup each step
    prefix = L.fill_constant_batch_size_like(
        input=init_ids, shape=[-1, K, T], dtype="float32", value=0.0)

    cond = L.less_than(x=counter, y=limit)
    while_op = L.While(cond=cond)
    with while_op.block():
        pre_ids = L.array_read(ids_array, counter)        # [B, K] int64
        pre_scores = L.array_read(scores_array, counter)  # [B, K]

        # prefix[:, :, t] = pre_ids
        t64 = L.cast(L.reshape(counter, shape=[1, 1]), "int64")
        onehot_t = L.one_hot(t64, T)                      # [1, T]
        keep = L.elementwise_sub(
            x=L.fill_constant(shape=[1, T], dtype="float32", value=1.0),
            y=onehot_t)
        new_prefix = L.elementwise_add(
            x=L.elementwise_mul(x=prefix, y=keep),
            y=L.elementwise_mul(
                x=L.expand(L.unsqueeze(L.cast(pre_ids, "float32"),
                                       axes=[2]), [1, 1, T]),
                y=onehot_t))
        L.assign(new_prefix, prefix)

        tokens = L.cast(L.reshape(prefix, shape=[-1, T]), "int64")
        # trg embedding + pos enc: same prepare_encoder call as training
        dec_input = prepare_encoder(
            tokens, trg_pos_rep, trg_vocab_size, d_model, T, 0.0,
            pos_enc_param_name=POS_ENC_PARAM_NAMES[1])
        dec_output = decoder(dec_input, enc_rep, trg_slf_rep, trg_src_rep,
                             n_layer, n_head, d_key, d_value, d_model,
                             d_inner_hid)
        logits = fluid.layers.fc(input=dec_output, size=trg_vocab_size,
                                 bias_attr=False, num_flatten_dims=2)
        # logits at position t: mask-and-reduce (no dynamic slicing op
        # needed; XLA folds the one-hot contraction)
        step_logits = L.reduce_sum(
            L.elementwise_mul(
                x=logits, y=L.reshape(onehot_t, shape=[1, T, 1])),
            dim=1)                                        # [B*K, V]
        logp = L.log(L.softmax(L.reshape(
            step_logits, shape=[-1, K, trg_vocab_size])))  # [B, K, V]

        selected_ids, selected_scores, parent = L.beam_search(
            pre_ids=pre_ids, pre_scores=pre_scores, ids=None, scores=logp,
            beam_size=K, end_id=eos_id, return_parent_idx=True)

        # reorder prefixes to follow their selected parent beams
        onehot_p = L.one_hot(parent, K)                   # [B, K, Ksrc]
        L.assign(L.matmul(onehot_p, prefix), prefix)

        L.increment(counter, 1, in_place=True)
        L.array_write(selected_ids, counter, ids_array)
        L.array_write(selected_scores, counter, scores_array)
        L.array_write(parent, counter, parent_array)
        L.less_than(x=counter, y=limit, cond=cond)

    return L.beam_search_decode(ids_array, scores_array,
                                parent_idx=parent_array, end_id=eos_id)


def build_cached_decode(src_vocab_size, trg_vocab_size, max_length,
                        n_layer=2, n_head=4, d_key=16, d_value=16,
                        d_model=64, d_inner_hid=128, beam_size=2,
                        max_out_len=None, bos_id=1, eos_id=2, fuse_qkv=False):
    """Incremental beam decode with per-layer self-attention KV caches —
    the TPU-native upgrade over build_decode (and over the reference era,
    which re-ran the whole decoder on the growing prefix each step,
    python/paddle/fluid's transformer infer path): step t computes ONE
    query position and attends its cached keys, so total decode FLOPs
    drop from O(T^2) decoder runs to O(T), with the caches living as
    while_loop carries (beam-reordered by parent via one_hot matmul —
    static shapes end to end).

    Built under a fresh unique_name.guard with the SAME parameter-creation
    sequence as `transformer`, so every weight shares its training name
    and the decode program runs in the training scope. Feeds: src_word,
    src_pos, src_slf_attn_bias, src_len [B,1] int32 (cross-attention key
    padding), init_ids, init_scores. Returns
    (sentence_ids [B,K,C], sentence_scores [B,K]) — must match
    build_decode token-for-token (tested)."""
    if fuse_qkv:
        raise NotImplementedError(
            "the decode builders create the unfused q/k/v weight layout; "
            "decode a fuse_qkv-trained scope is not supported — train "
            "with use_qkv_fusion=False for decode interop")

    L = fluid.layers
    K = beam_size
    T = max_length
    limit_steps = T - 1 if max_out_len is None else min(max_out_len, T - 1)

    src_word = L.data("src_word", [T], dtype="int64")
    src_pos = L.data("src_pos", [T], dtype="int64")
    src_slf = L.data("src_slf_attn_bias", [n_head, T, T])
    src_len = L.data("src_len", [1], dtype="int32")
    init_ids = L.data("init_ids", [K], dtype="int64")
    init_scores = L.data("init_scores", [K])

    enc_input = prepare_encoder(
        src_word, src_pos, src_vocab_size, d_model, T, 0.0,
        pos_enc_param_name=POS_ENC_PARAM_NAMES[0])
    enc_output = encoder(enc_input, src_slf, n_layer, n_head, d_key,
                         d_value, d_model, d_inner_hid)

    def beam_rep(x, tail_dims):
        r = L.expand(L.unsqueeze(x, axes=[1]),
                     [1, K] + [1] * len(tail_dims))
        return L.reshape(r, shape=[-1] + list(tail_dims))

    enc_rep = beam_rep(enc_output, [T, d_model])            # [B*K, Ts, D]
    src_len_rep = L.cast(beam_rep(L.cast(src_len, "float32"), [1]),
                         "float32")                          # [B*K, 1]

    counter = L.zeros(shape=[1], dtype="int32")
    counter.stop_gradient = True
    limit = L.fill_constant(shape=[1], dtype="int32", value=limit_steps)

    ids_array = L.create_array("int64", capacity=limit_steps + 1)
    scores_array = L.create_array("float32", capacity=limit_steps + 1)
    parent_array = L.create_array("int32", capacity=limit_steps + 1)
    L.array_write(init_ids, counter, ids_array)
    L.array_write(init_scores, counter, scores_array)
    init_parent = L.fill_constant_batch_size_like(
        input=init_ids, shape=[-1, K], dtype="int32", value=0)
    L.array_write(init_parent, counter, parent_array)

    # per-layer self-attention KV caches [B*K, T, H*d]
    caches = []
    for _ in range(n_layer):
        ck = L.fill_constant_batch_size_like(
            input=enc_rep, shape=[-1, T, n_head * d_key],
            dtype="float32", value=0.0)
        cv = L.fill_constant_batch_size_like(
            input=enc_rep, shape=[-1, T, n_head * d_value],
            dtype="float32", value=0.0)
        caches.append((ck, cv))

    # constant position row [1, 1, 1, T] for building step masks
    pos_row = L.assign(np.arange(T, dtype="float32").reshape(1, 1, 1, T))

    def one_query_attention(q, ks, vs, valid, dk, dv):
        """q [BK,1,H*dk] attends ks/vs [BK,Tk,H*dk] under `valid`
        [*,1,1,Tk] (1 = attendable) — the O(Tk) cached step."""
        qh = L.transpose(L.reshape(q, shape=[0, 1, n_head, dk]),
                         perm=[0, 2, 1, 3])                  # [BK,H,1,dk]
        kh = L.transpose(L.reshape(ks, shape=[0, -1, n_head, dk]),
                         perm=[0, 2, 1, 3])
        vh = L.transpose(L.reshape(vs, shape=[0, -1, n_head, dv]),
                         perm=[0, 2, 1, 3])
        sc = L.scale(L.matmul(qh, kh, transpose_y=True),
                     scale=dk ** -0.5)                       # [BK,H,1,Tk]
        sc = sc + (valid - 1.0) * 1e9
        w = L.softmax(sc)
        ctx = L.matmul(w, vh)                                # [BK,H,1,dv]
        return L.reshape(L.transpose(ctx, perm=[0, 2, 1, 3]),
                         shape=[0, 1, n_head * dv])

    cond = L.less_than(x=counter, y=limit)
    while_op = L.While(cond=cond)
    with while_op.block():
        pre_ids = L.array_read(ids_array, counter)           # [B, K]
        pre_scores = L.array_read(scores_array, counter)

        t_f = L.cast(L.reshape(counter, shape=[1, 1]), "float32")
        t64 = L.cast(L.reshape(counter, shape=[1, 1]), "int64")
        onehot_t = L.one_hot(t64, T)                         # [1, T]

        # current token embedding + position encoding (same call order as
        # prepare_encoder: word emb then pos table)
        cur = L.reshape(L.cast(pre_ids, "int64"), shape=[-1, 1])
        word_emb = L.embedding(
            cur, size=[trg_vocab_size, d_model],
            param_attr=fluid.ParamAttr(
                initializer=fluid.initializer.Normal(
                    0., d_model ** -0.5)))
        word_emb = L.scale(x=word_emb, scale=d_model ** 0.5)
        pos_ids = L.cast(
            L.fill_constant_batch_size_like(
                input=cur, shape=[-1, 1], dtype="int32", value=0)
            + L.cast(L.reshape(counter, shape=[1]), "int32"), "int64")
        pos_enc = L.embedding(
            pos_ids, size=[T, d_model],
            param_attr=fluid.ParamAttr(
                name=POS_ENC_PARAM_NAMES[1], trainable=False,
                initializer=fluid.initializer.NumpyArrayInitializer(
                    position_encoding_init(T, d_model))))
        # embedding of [BK, 1] ids yields [BK, D] (reference lookup_table
        # squeezes the id column); restore the explicit one-step time axis so
        # every fc below sees [BK, 1, D] and creates [D, size] weights that
        # share shapes (and names) with the training program's.
        x = L.reshape(word_emb + pos_enc, shape=[-1, 1, d_model])

        # step masks: self-attn sees cache positions <= t; cross-attn sees
        # source positions < src_len
        t4 = L.reshape(t_f, shape=[1, 1, 1, 1])
        self_valid = L.clip(t4 + 1.0 - pos_row, min=0.0, max=1.0)
        cross_valid = L.clip(
            L.reshape(src_len_rep, shape=[-1, 1, 1, 1]) - pos_row,
            min=0.0, max=1.0)                                # [BK,1,1,T]

        new_caches = []
        for l in range(n_layer):
            ck, cv = caches[l]
            # EXACT training param order per decoder_layer: LN; self
            # q/k/v fc, out fc; LN; cross q/k/v fc, out fc; LN; ffn fc1/2
            xn = pre_post_process_layer(None, x, "n")
            q = L.fc(input=xn, size=d_key * n_head, bias_attr=False,
                     num_flatten_dims=2)
            k = L.fc(input=xn, size=d_key * n_head, bias_attr=False,
                     num_flatten_dims=2)
            v = L.fc(input=xn, size=d_value * n_head, bias_attr=False,
                     num_flatten_dims=2)
            # cache[:, t] = k / v (one_hot write, static shapes)
            keep = L.reshape(1.0 - onehot_t, shape=[1, T, 1])
            put = L.reshape(onehot_t, shape=[1, T, 1])
            ck = ck * keep + L.expand(k, [1, T, 1]) * put
            cv = cv * keep + L.expand(v, [1, T, 1]) * put
            new_caches.append((ck, cv))
            att = one_query_attention(q, ck, cv, self_valid, d_key,
                                      d_value)
            x = x + L.fc(input=att, size=d_model, bias_attr=False,
                         num_flatten_dims=2)

            xn = pre_post_process_layer(None, x, "n")
            q2 = L.fc(input=xn, size=d_key * n_head, bias_attr=False,
                      num_flatten_dims=2)
            ek = L.fc(input=enc_rep, size=d_key * n_head, bias_attr=False,
                      num_flatten_dims=2)
            ev = L.fc(input=enc_rep, size=d_value * n_head,
                      bias_attr=False, num_flatten_dims=2)
            att2 = one_query_attention(q2, ek, ev, cross_valid, d_key,
                                       d_value)
            x = x + L.fc(input=att2, size=d_model, bias_attr=False,
                         num_flatten_dims=2)

            xn = pre_post_process_layer(None, x, "n")
            x = x + positionwise_feed_forward(xn, d_inner_hid, d_model)

        dec_out = pre_post_process_layer(None, x, "n")       # final LN
        logits = L.fc(input=dec_out, size=trg_vocab_size, bias_attr=False,
                      num_flatten_dims=2)                    # [BK, 1, V]
        logp = L.log(L.softmax(L.reshape(
            logits, shape=[-1, K, trg_vocab_size])))         # [B, K, V]

        selected_ids, selected_scores, parent = L.beam_search(
            pre_ids=pre_ids, pre_scores=pre_scores, ids=None, scores=logp,
            beam_size=K, end_id=eos_id, return_parent_idx=True)

        # reorder every cache row to follow its selected parent beam
        onehot_p = L.one_hot(parent, K)                      # [B, K, Ksrc]
        for l, (ck, cv) in enumerate(new_caches):
            ckb = L.reshape(ck, shape=[-1, K, T * n_head * d_key])
            cvb = L.reshape(cv, shape=[-1, K, T * n_head * d_value])
            L.assign(L.reshape(L.matmul(onehot_p, ckb),
                               shape=[-1, T, n_head * d_key]),
                     caches[l][0])
            L.assign(L.reshape(L.matmul(onehot_p, cvb),
                               shape=[-1, T, n_head * d_value]),
                     caches[l][1])

        L.increment(counter, 1, in_place=True)
        L.array_write(selected_ids, counter, ids_array)
        L.array_write(selected_scores, counter, scores_array)
        L.array_write(parent, counter, parent_array)
        L.less_than(x=counter, y=limit, cond=cond)

    return L.beam_search_decode(ids_array, scores_array,
                                parent_idx=parent_array, end_id=eos_id)


def prepare_cached_decode_batch(src_seqs, max_length, n_head, beam_size,
                                bos_id=1, pad_id=0):
    """Feed arrays for build_cached_decode: encoder feeds + src_len +
    beam init (no [H,T,T] target bias tensors needed)."""
    feeds = prepare_decode_batch(src_seqs, max_length, n_head, beam_size,
                                 bos_id=bos_id, pad_id=pad_id)
    feeds["src_len"] = np.array(
        [[min(len(s), max_length)] for s in src_seqs], "int32")
    for k in ("trg_pos_full", "trg_slf_attn_bias", "trg_src_attn_bias"):
        feeds.pop(k)
    return feeds


def prepare_decode_batch(src_seqs, max_length, n_head, beam_size,
                         bos_id=1, pad_id=0):
    """Feed arrays for build_decode: encoder feeds + beam init."""
    b = len(src_seqs)
    neg = -1e9
    src = np.full((b, max_length), pad_id, "int64")
    src_pos = np.zeros((b, max_length), "int64")
    src_bias = np.zeros((b, n_head, max_length, max_length), "float32")
    cross_bias = np.zeros((b, n_head, max_length, max_length), "float32")
    causal = np.triu(np.full((max_length, max_length), neg, "float32"), 1)
    trg_bias = np.tile(causal[None, None], (b, n_head, 1, 1))
    for i, s in enumerate(src_seqs):
        s = list(s)[:max_length]
        src[i, :len(s)] = s
        src_pos[i, :len(s)] = np.arange(len(s))
        src_bias[i, :, :, len(s):] = neg
        cross_bias[i, :, :, len(s):] = neg
    init_ids = np.full((b, beam_size), bos_id, "int64")
    init_scores = np.zeros((b, beam_size), "float32")
    init_scores[:, 1:] = neg  # break initial beam symmetry
    return {
        "src_word": src, "src_pos": src_pos, "src_slf_attn_bias": src_bias,
        "trg_pos_full": np.tile(np.arange(max_length, dtype="int64")[None],
                                (b, 1)),
        "trg_slf_attn_bias": trg_bias.astype("float32"),
        "trg_src_attn_bias": cross_bias,
        "init_ids": init_ids, "init_scores": init_scores,
    }


def prepare_batch(src_seqs, trg_seqs, max_length, n_head, pad_id=0,
                  fused=False):
    """Pack python token lists into the dense feed arrays (9 classic feeds,
    or — fused=True, for a use_fused_attention program — src_len/trg_len
    instead of the three [H, T, T] bias tensors)."""
    b = len(src_seqs)
    src = np.full((b, max_length), pad_id, "int64")
    src_pos = np.zeros((b, max_length), "int64")
    trg = np.full((b, max_length), pad_id, "int64")
    trg_pos = np.zeros((b, max_length), "int64")
    lbl = np.full((b, max_length, 1), pad_id, "int64")
    lbl_w = np.zeros((b, max_length, 1), "float32")
    src_len = np.zeros((b, 1), "int32")
    trg_len = np.zeros((b, 1), "int32")
    neg = -1e9
    if not fused:
        src_bias = np.zeros((b, n_head, max_length, max_length), "float32")
        trg_bias = np.zeros((b, n_head, max_length, max_length), "float32")
        cross_bias = np.zeros((b, n_head, max_length, max_length),
                              "float32")
        causal = np.triu(np.full((max_length, max_length), neg, "float32"),
                         1)
    for i, (s, t) in enumerate(zip(src_seqs, trg_seqs)):
        s = list(s)[:max_length]
        # teacher forcing: input <s>+t[:-1], label t
        t_in = [1] + list(t[:-1])
        t_in = t_in[:max_length]
        src[i, :len(s)] = s
        src_pos[i, :len(s)] = np.arange(len(s))
        trg[i, :len(t_in)] = t_in
        trg_pos[i, :len(t_in)] = np.arange(len(t_in))
        tl = min(len(t), max_length)
        lbl[i, :tl, 0] = list(t)[:tl]
        lbl_w[i, :tl, 0] = 1.0
        src_len[i, 0] = len(s)
        trg_len[i, 0] = len(t_in)
        if not fused:
            src_bias[i, :, :, len(s):] = neg
            trg_bias[i] = causal[None]
            trg_bias[i, :, :, len(t_in):] = neg
            cross_bias[i, :, :, len(s):] = neg
    feeds = {"src_word": src, "src_pos": src_pos, "trg_word": trg,
             "trg_pos": trg_pos, "lbl_word": lbl, "lbl_weight": lbl_w}
    if fused:
        feeds["src_len"] = src_len
        feeds["trg_len"] = trg_len
    else:
        feeds["src_slf_attn_bias"] = src_bias
        feeds["trg_slf_attn_bias"] = trg_bias
        feeds["trg_src_attn_bias"] = cross_bias
    return feeds
