"""OCR recognition with CTC (CRNN-style).

Parity: the fluid benchmark's ocr_recognition/crnn_ctc_model (conv-bn-pool
groups -> im2sequence column slicing -> bidirectional GRU -> per-step
class logits -> warpctc), the model family SURVEY.md lists under
"transformer & OCR-CTC (fluid benchmark dir)". Decode/eval via
ctc_greedy_decoder + edit_distance.
"""
import paddle_tpu as fluid


def conv_bn_pool(input, group, out_ch, act="relu", is_test=False,
                 pool_stride=2):
    tmp = input
    for i in range(group):
        tmp = fluid.layers.conv2d(
            input=tmp, num_filters=out_ch, filter_size=3, padding=1,
            bias_attr=False)
        tmp = fluid.layers.batch_norm(input=tmp, act=act, is_test=is_test)
    return fluid.layers.pool2d(
        input=tmp, pool_size=2, pool_type="max", pool_stride=pool_stride)


def ocr_convs(input, is_test=False, channels=(16, 32, 64)):
    tmp = input
    for ch in channels:
        tmp = conv_bn_pool(tmp, 2, ch, is_test=is_test)
    return tmp


def encoder_net(images, num_classes, rnn_hidden_size=64, is_test=False,
                channels=(16, 32, 64)):
    """Images [B, 1, H, W] -> per-column logits sequence [B, W', C+1]."""
    conv_features = ocr_convs(images, is_test=is_test, channels=channels)
    # slice the feature map into a width-major sequence: each timestep is
    # one column (full height x channels)
    h = conv_features.shape[2]
    sliced_feature = fluid.layers.im2sequence(
        input=conv_features, filter_size=(h, 1), stride=(1, 1))

    fc_1 = fluid.layers.fc(input=sliced_feature, size=rnn_hidden_size * 3)
    fc_2 = fluid.layers.fc(input=sliced_feature, size=rnn_hidden_size * 3)
    gru_forward = fluid.layers.dynamic_gru(
        input=fc_1, size=rnn_hidden_size, candidate_activation="relu")
    gru_backward = fluid.layers.dynamic_gru(
        input=fc_2, size=rnn_hidden_size, is_reverse=True,
        candidate_activation="relu")

    return fluid.layers.fc(input=[gru_forward, gru_backward],
                           size=num_classes + 1)


def ctc_train_net(images, label, num_classes, learning_rate=1e-3,
                  rnn_hidden_size=64, channels=(16, 32, 64)):
    """Returns (sum_cost, decoded, edit_distance_out, seq_num)."""
    fc_out = encoder_net(images, num_classes,
                         rnn_hidden_size=rnn_hidden_size, channels=channels)
    cost = fluid.layers.warpctc(
        input=fc_out, label=label, blank=num_classes, norm_by_times=True)
    sum_cost = fluid.layers.reduce_sum(cost)
    optimizer = fluid.optimizer.Momentum(
        learning_rate=learning_rate, momentum=0.9)
    optimizer.minimize(sum_cost)

    decoded_out = fluid.layers.ctc_greedy_decoder(
        input=fc_out, blank=num_classes)
    error, seq_num = fluid.layers.edit_distance(
        input=decoded_out, label=label, normalized=True)
    return sum_cost, decoded_out, error, seq_num
