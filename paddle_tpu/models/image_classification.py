"""Image classification models: ResNet, VGG, AlexNet-ish.

Parity: benchmark/paddle/image/{resnet.py,vgg.py,alexnet.py} and the fluid
book chapter 03 (image_classification). ResNet-50 is the flagship/benchmark
model (BASELINE.json north star).
"""
import paddle_tpu as fluid


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None, is_test=False):
    conv = fluid.layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=(filter_size - 1) // 2, groups=groups,
        act=None, bias_attr=False)
    return fluid.layers.batch_norm(input=conv, act=act, is_test=is_test)


def shortcut(input, ch_out, stride, is_test=False):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, is_test=is_test)
    return input


def bottleneck_block(input, num_filters, stride, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu", is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride, act="relu",
                          is_test=is_test)
    conv2 = conv_bn_layer(conv1, num_filters * 4, 1, act=None,
                          is_test=is_test)
    short = shortcut(input, num_filters * 4, stride, is_test=is_test)
    return fluid.layers.elementwise_add(x=short, y=conv2, act="relu")


def basic_block(input, num_filters, stride, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 3, stride=stride, act="relu",
                          is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3, act=None, is_test=is_test)
    short = shortcut(input, num_filters, stride, is_test=is_test)
    return fluid.layers.elementwise_add(x=short, y=conv1, act="relu")


RESNET_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def resnet_imagenet(input, class_dim=1000, depth=50, is_test=False):
    """ResNet for 224x224 ImageNet (reference: benchmark resnet.py layers=50)."""
    kind, counts = RESNET_CFG[depth]
    block_fn = bottleneck_block if kind == "bottleneck" else basic_block
    conv = conv_bn_layer(input, 64, 7, stride=2, act="relu", is_test=is_test)
    pool = fluid.layers.pool2d(input=conv, pool_size=3, pool_stride=2,
                               pool_padding=1, pool_type="max")
    filters = [64, 128, 256, 512]
    for stage, n in enumerate(counts):
        for i in range(n):
            stride = 2 if i == 0 and stage > 0 else 1
            pool = block_fn(pool, filters[stage], stride, is_test=is_test)
    pool = fluid.layers.pool2d(input=pool, pool_type="avg",
                               global_pooling=True)
    return fluid.layers.fc(input=pool, size=class_dim, act="softmax")


def resnet_cifar10(input, class_dim=10, depth=32, is_test=False):
    """Reference: fluid book ch.03 resnet_cifar10."""
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv = conv_bn_layer(input, 16, 3, act="relu", is_test=is_test)
    for stage, nf in enumerate([16, 32, 64]):
        for i in range(n):
            stride = 2 if i == 0 and stage > 0 else 1
            conv = basic_block(conv, nf, stride, is_test=is_test)
    pool = fluid.layers.pool2d(input=conv, pool_type="avg",
                               global_pooling=True)
    return fluid.layers.fc(input=pool, size=class_dim, act="softmax")


def vgg16(input, class_dim=1000, is_test=False):
    """Reference: benchmark vgg.py / book ch.03 vgg_bn_drop."""
    def conv_block(ipt, num_filter, groups):
        return fluid.nets.img_conv_group(
            input=ipt, pool_size=2, pool_stride=2,
            conv_num_filter=[num_filter] * groups, conv_filter_size=3,
            conv_act="relu", conv_with_batchnorm=True, pool_type="max")

    conv1 = conv_block(input, 64, 2)
    conv2 = conv_block(conv1, 128, 2)
    conv3 = conv_block(conv2, 256, 3)
    conv4 = conv_block(conv3, 512, 3)
    conv5 = conv_block(conv4, 512, 3)
    fc1 = fluid.layers.fc(input=conv5, size=4096, act=None)
    bn = fluid.layers.batch_norm(input=fc1, act="relu", is_test=is_test)
    drop = fluid.layers.dropout(x=bn, dropout_prob=0.5, is_test=is_test)
    fc2 = fluid.layers.fc(input=drop, size=4096, act=None)
    return fluid.layers.fc(input=fc2, size=class_dim, act="softmax")


def build_train(model="resnet50", class_dim=1000, image_shape=(3, 224, 224),
                learning_rate=0.01, momentum=0.9, is_test=False,
                use_softmax_xent_fusion=True, use_bf16=False,
                uint8_input=False):
    """Build the full training graph (reference: benchmark/fluid style).

    use_bf16 turns on the TPU mixed-precision path for the enclosing main
    program (Program.enable_mixed_precision): bf16 MXU compute, f32 master
    params — SURVEY §7 M5.

    uint8_input: the image feed is raw uint8 pixels, normalized to
    [0, 1) ON DEVICE (cast + scale fuse into the first conv). The
    standard TPU input-pipeline layout: 4x less host->device traffic
    than float32 feeds — the feeder measurement decoupled from link
    bandwidth (round-4 weak #5).

    Returns (image, label, avg_cost, acc_top1).
    """
    if use_bf16:
        fluid.default_main_program().enable_mixed_precision()
    if uint8_input:
        raw = fluid.layers.data(name="image", shape=list(image_shape),
                                dtype="uint8")
        image = fluid.layers.scale(
            fluid.layers.cast(raw, dtype="float32"), scale=1.0 / 255.0)
    else:
        image = fluid.layers.data(name="image", shape=list(image_shape),
                                  dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    if model.startswith("resnet"):
        depth = int(model[len("resnet"):] or 50)
        if image_shape[-1] <= 64:
            predict = resnet_cifar10(image, class_dim,
                                     depth if depth in (20, 32, 44, 56) else 32,
                                     is_test=is_test)
        else:
            predict = resnet_imagenet(image, class_dim, depth,
                                      is_test=is_test)
    elif model == "vgg16":
        predict = vgg16(image, class_dim, is_test=is_test)
    elif model == "alexnet":
        predict = alexnet(image, class_dim, is_test=is_test)
    elif model == "googlenet":
        predict = googlenet(image, class_dim, is_test=is_test)
    elif model.startswith("se_resnext"):
        suffix = model[len("se_resnext"):] or "50"
        if suffix not in ("50", "101", "152"):
            raise ValueError("unknown model %r" % model)
        predict = se_resnext(image, class_dim, int(suffix), is_test=is_test)
    else:
        raise ValueError("unknown model %r" % model)
    cost = fluid.layers.cross_entropy(input=predict, label=label)
    avg_cost = fluid.layers.mean(x=cost)
    acc = fluid.layers.accuracy(input=predict, label=label)
    if not is_test:
        opt = fluid.optimizer.Momentum(learning_rate=learning_rate,
                                       momentum=momentum)
        opt.minimize(avg_cost)
    return image, label, avg_cost, acc


def alexnet(input, class_dim=1000, is_test=False):
    """Reference: benchmark/paddle/image/alexnet.py (legacy v2 benchmark)."""
    conv1 = fluid.layers.conv2d(input=input, num_filters=96, filter_size=11,
                                stride=4, act="relu")
    pool1 = fluid.layers.pool2d(input=conv1, pool_size=3, pool_stride=2,
                                pool_type="max")
    norm1 = fluid.layers.lrn(input=pool1, n=5, alpha=0.0001, beta=0.75)
    conv2 = fluid.layers.conv2d(input=norm1, num_filters=256, filter_size=5,
                                padding=2, groups=1, act="relu")
    pool2 = fluid.layers.pool2d(input=conv2, pool_size=3, pool_stride=2,
                                pool_type="max")
    norm2 = fluid.layers.lrn(input=pool2, n=5, alpha=0.0001, beta=0.75)
    conv3 = fluid.layers.conv2d(input=norm2, num_filters=384, filter_size=3,
                                padding=1, act="relu")
    conv4 = fluid.layers.conv2d(input=conv3, num_filters=384, filter_size=3,
                                padding=1, act="relu")
    conv5 = fluid.layers.conv2d(input=conv4, num_filters=256, filter_size=3,
                                padding=1, act="relu")
    pool3 = fluid.layers.pool2d(input=conv5, pool_size=3, pool_stride=2,
                                pool_type="max")
    fc1 = fluid.layers.fc(input=pool3, size=4096, act="relu")
    drop1 = fluid.layers.dropout(x=fc1, dropout_prob=0.5, is_test=is_test)
    fc2 = fluid.layers.fc(input=drop1, size=4096, act="relu")
    drop2 = fluid.layers.dropout(x=fc2, dropout_prob=0.5, is_test=is_test)
    return fluid.layers.fc(input=drop2, size=class_dim, act="softmax")


def _inception(input, c1, c3r, c3, c5r, c5, proj):
    """GoogLeNet inception module (benchmark/paddle/image/googlenet.py)."""
    b1 = fluid.layers.conv2d(input=input, num_filters=c1, filter_size=1,
                             act="relu")
    b3 = fluid.layers.conv2d(input=input, num_filters=c3r, filter_size=1,
                             act="relu")
    b3 = fluid.layers.conv2d(input=b3, num_filters=c3, filter_size=3,
                             padding=1, act="relu")
    b5 = fluid.layers.conv2d(input=input, num_filters=c5r, filter_size=1,
                             act="relu")
    b5 = fluid.layers.conv2d(input=b5, num_filters=c5, filter_size=5,
                             padding=2, act="relu")
    bp = fluid.layers.pool2d(input=input, pool_size=3, pool_stride=1,
                             pool_padding=1, pool_type="max")
    bp = fluid.layers.conv2d(input=bp, num_filters=proj, filter_size=1,
                             act="relu")
    return fluid.layers.concat(input=[b1, b3, b5, bp], axis=1)


def googlenet(input, class_dim=1000, is_test=False):
    """Reference: benchmark/paddle/image/googlenet.py (main tower; the two
    auxiliary classifier heads are a training-era regularizer the fluid
    benchmark also drops)."""
    conv = fluid.layers.conv2d(input=input, num_filters=64, filter_size=7,
                               stride=2, padding=3, act="relu")
    pool = fluid.layers.pool2d(input=conv, pool_size=3, pool_stride=2,
                               pool_type="max")
    conv = fluid.layers.conv2d(input=pool, num_filters=64, filter_size=1,
                               act="relu")
    conv = fluid.layers.conv2d(input=conv, num_filters=192, filter_size=3,
                               padding=1, act="relu")
    pool = fluid.layers.pool2d(input=conv, pool_size=3, pool_stride=2,
                               pool_type="max")
    ince = _inception(pool, 64, 96, 128, 16, 32, 32)     # 3a
    ince = _inception(ince, 128, 128, 192, 32, 96, 64)   # 3b
    pool = fluid.layers.pool2d(input=ince, pool_size=3, pool_stride=2,
                               pool_type="max")
    ince = _inception(pool, 192, 96, 208, 16, 48, 64)    # 4a
    ince = _inception(ince, 160, 112, 224, 24, 64, 64)   # 4b
    ince = _inception(ince, 128, 128, 256, 24, 64, 64)   # 4c
    ince = _inception(ince, 112, 144, 288, 32, 64, 64)   # 4d
    ince = _inception(ince, 256, 160, 320, 32, 128, 128) # 4e
    pool = fluid.layers.pool2d(input=ince, pool_size=3, pool_stride=2,
                               pool_type="max")
    ince = _inception(pool, 256, 160, 320, 32, 128, 128) # 5a
    ince = _inception(ince, 384, 192, 384, 48, 128, 128) # 5b
    pool = fluid.layers.pool2d(input=ince, pool_type="avg",
                               global_pooling=True)
    drop = fluid.layers.dropout(x=pool, dropout_prob=0.4, is_test=is_test)
    return fluid.layers.fc(input=drop, size=class_dim, act="softmax")


def squeeze_excitation(input, num_channels, reduction_ratio=16):
    pool = fluid.layers.pool2d(input=input, pool_type="avg",
                               global_pooling=True)
    squeeze = fluid.layers.fc(input=pool,
                              size=num_channels // reduction_ratio,
                              act="relu")
    excitation = fluid.layers.fc(input=squeeze, size=num_channels,
                                 act="sigmoid")
    return fluid.layers.elementwise_mul(x=input, y=excitation, axis=0)


def se_bottleneck_block(input, num_filters, stride, cardinality=32,
                        reduction_ratio=16, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu", is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride,
                          groups=cardinality, act="relu", is_test=is_test)
    conv2 = conv_bn_layer(conv1, num_filters * 2, 1, act=None,
                          is_test=is_test)
    scale = squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    short = shortcut(input, num_filters * 2, stride, is_test=is_test)
    return fluid.layers.elementwise_add(x=short, y=scale, act="relu")


def se_resnext(input, class_dim=1000, depth=50, cardinality=32,
               reduction_ratio=16, is_test=False):
    """SE-ResNeXt-50/101/152 (fluid benchmark models/se_resnext.py)."""
    counts = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}[depth]
    conv = conv_bn_layer(input, 64, 7, stride=2, act="relu", is_test=is_test)
    pool = fluid.layers.pool2d(input=conv, pool_size=3, pool_stride=2,
                               pool_padding=1, pool_type="max")
    filters = [128, 256, 512, 1024]
    for stage, n in enumerate(counts):
        for i in range(n):
            stride = 2 if i == 0 and stage > 0 else 1
            pool = se_bottleneck_block(
                pool, filters[stage], stride, cardinality, reduction_ratio,
                is_test=is_test)
    pool = fluid.layers.pool2d(input=pool, pool_type="avg",
                               global_pooling=True)
    drop = fluid.layers.dropout(x=pool, dropout_prob=0.5, is_test=is_test)
    return fluid.layers.fc(input=drop, size=class_dim, act="softmax")
