"""Device places.

Parity: paddle/fluid/platform/place.h (CPUPlace / CUDAPlace) — plus the
TPUPlace this framework exists for. A Place selects the JAX backend the
Executor dispatches to; TPUPlace is the default when TPU devices exist.
CUDAPlace is accepted as an alias for "the accelerator" so unmodified fluid
scripts run (the reference's CUDAPlace(0) becomes the TPU chip).
"""
import jax


class Place(object):
    backend = None

    def device(self):
        devs = jax.devices(self.backend) if self.backend else jax.devices()
        return devs[self.device_id if hasattr(self, "device_id") else 0]

    def __repr__(self):
        return type(self).__name__ + "()"


class CPUPlace(Place):
    backend = "cpu"


class TPUPlace(Place):
    """Native TPU execution (BASELINE.json north star: platform::TPUPlace)."""

    def __init__(self, device_id=0):
        self.device_id = device_id

    def device(self):
        try:
            devs = [d for d in jax.devices() if d.platform != "cpu"]
        except RuntimeError:
            devs = []
        if not devs:
            return jax.devices("cpu")[0]
        return devs[self.device_id % len(devs)]


class CUDAPlace(TPUPlace):
    """Compatibility alias: reference scripts that say CUDAPlace(0) get the
    accelerator (TPU) — no GPU in the loop."""


def is_compiled_with_cuda():
    return False


def is_compiled_with_tpu():
    return any(d.platform not in ("cpu",) for d in jax.devices())
