"""Learning-rate decay schedules built as program sub-graphs.

Parity: python/paddle/fluid/layers/learning_rate_scheduler.py (reference
lines 35-210: exponential/natural_exp/inverse_time/polynomial/piecewise
decay, each built from a persistable `@LR_DECAY_COUNTER@` step counter).
`noam_decay` (the transformer warmup schedule) is included for the
benchmark transformer model.

TPU notes: the whole schedule is ordinary ops inside the jitted training
program, so XLA folds it into the update step — there is no host-side
schedule computation or recompilation per step. The counter is a real
persistable var threaded through the donated-params state like any other.
"""
from . import nn
from . import ops
from . import tensor
from . import control_flow

__all__ = [
    'exponential_decay', 'natural_exp_decay', 'inverse_time_decay',
    'polynomial_decay', 'piecewise_decay', 'noam_decay',
]


def _decay_step_counter():
    # the first global step is zero in learning rate decay. All schedules
    # share one counter (reference parity) so every schedule derives its
    # step from the same begin=0 base — noam shifts by +1 in-graph.
    global_step = nn.autoincreased_step_counter(
        counter_name='@LR_DECAY_COUNTER@', begin=0, step=1)
    return tensor.cast(global_step, 'float32')


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    """lr = learning_rate * d_model^-0.5 * min(step^-0.5, step*warmup^-1.5).

    The "Attention is All You Need" schedule (steps count from 1).
    """
    global_step = _decay_step_counter() + 1.0
    a = global_step ** -0.5
    b = (warmup_steps ** -1.5) * global_step
    return learning_rate * (d_model ** -0.5) * ops.elementwise_min(a, b)


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    """lr * decay_rate ^ (global_step / decay_steps)."""
    global_step = _decay_step_counter()
    div_res = global_step / decay_steps
    if staircase:
        div_res = ops.floor(div_res)
    return learning_rate * (decay_rate ** div_res)


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    """lr * exp(-decay_rate * (global_step / decay_steps))."""
    global_step = _decay_step_counter()
    div_res = global_step / decay_steps
    if staircase:
        div_res = ops.floor(div_res)
    return learning_rate * ops.exp(-1 * decay_rate * div_res)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    """lr / (1 + decay_rate * global_step / decay_steps)."""
    global_step = _decay_step_counter()
    div_res = global_step / decay_steps
    if staircase:
        div_res = ops.floor(div_res)
    return learning_rate / (1 + decay_rate * div_res)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    """(lr - end_lr) * (1 - step/decay_steps)^power + end_lr."""
    global_step = _decay_step_counter()
    if cycle:
        div_res = ops.ceil(global_step / decay_steps)
        zero_var = tensor.fill_constant(shape=[1], dtype='float32', value=0.0)
        one_var = tensor.fill_constant(shape=[1], dtype='float32', value=1.0)
        with control_flow.Switch() as switch:
            with switch.case(control_flow.equal(global_step, zero_var)):
                tensor.assign(input=one_var, output=div_res)
        decay_steps_v = decay_steps * div_res
    else:
        decay_steps_var = tensor.fill_constant(
            shape=[1], dtype='float32', value=float(decay_steps))
        global_step = ops.elementwise_min(global_step, decay_steps_var)
        decay_steps_v = decay_steps
    return ((learning_rate - end_learning_rate) *
            ((1 - global_step / decay_steps_v) ** power) + end_learning_rate)


def piecewise_decay(boundaries, values):
    """Step function: values[i] while step < boundaries[i], else values[-1]."""
    if len(values) - len(boundaries) != 1:
        raise ValueError("len(values) - len(boundaries) should be 1")
    global_step = _decay_step_counter()
    from ..core import unique_name
    lr = tensor.create_global_var(
        shape=[1], value=0.0, dtype='float32', persistable=True,
        name=unique_name.generate("learning_rate"))
    with control_flow.Switch() as switch:
        for i in range(len(boundaries)):
            boundary_val = tensor.fill_constant(
                shape=[1], dtype='float32', value=float(boundaries[i]))
            value_var = tensor.fill_constant(
                shape=[1], dtype='float32', value=float(values[i]))
            with switch.case(control_flow.less_than(global_step,
                                                    boundary_val)):
                tensor.assign(value_var, lr)
        last_value_var = tensor.fill_constant(
            shape=[1], dtype='float32', value=float(values[-1]))
        with switch.default():
            tensor.assign(last_value_var, lr)
    return lr
