"""fluid.layers-equivalent namespace.

Parity: python/paddle/fluid/layers/__init__.py — flat re-export of nn, ops,
tensor, io, control_flow (+ detection/metric added with their milestones).
"""
from . import nn
from .nn import *          # noqa: F401,F403
from . import ops
from .ops import *         # noqa: F401,F403
from . import tensor
from .tensor import *      # noqa: F401,F403
from . import io
from .io import *          # noqa: F401,F403
from . import sequence
from .sequence import *    # noqa: F401,F403
from . import control_flow
from .control_flow import *  # noqa: F401,F403
from . import learning_rate_scheduler
from .learning_rate_scheduler import *  # noqa: F401,F403
from . import detection
from .detection import *   # noqa: F401,F403
from . import parallel_layers
from .parallel_layers import *  # noqa: F401,F403
from . import extras
from .extras import *      # noqa: F401,F403
from .math_op_patch import monkey_patch_variable

monkey_patch_variable()

__all__ = (nn.__all__ + ops.__all__ + tensor.__all__ + io.__all__ +
           sequence.__all__ + control_flow.__all__ +
           learning_rate_scheduler.__all__ + detection.__all__ +
           parallel_layers.__all__ + extras.__all__)
