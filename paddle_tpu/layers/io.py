"""Data-input layers.

Parity: python/paddle/fluid/layers/io.py — `data` declares a feed Variable
(batch dim prepended as -1, like the reference's append_batch_size).
"""
from ..core.framework import default_main_program, default_startup_program

__all__ = ["data"]


def data(name, shape, append_batch_size=True, dtype="float32", lod_level=0,
         type=None, stop_gradient=True):
    shape = list(shape)
    if append_batch_size:
        if all(s >= 0 for s in shape):
            shape = [-1] + shape
        # if user already put a -1 in shape, don't prepend another batch dim
    main = default_main_program().global_block().create_var(
        name=name, shape=shape, dtype=dtype, lod_level=lod_level,
        stop_gradient=stop_gradient, is_data=True)
    return main
