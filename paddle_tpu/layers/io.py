"""Data-input layers.

Parity: python/paddle/fluid/layers/io.py — `data` declares a feed Variable
(batch dim prepended as -1, like the reference's append_batch_size);
open_recordio_file/open_files + the reader decorators + read_file mirror
layers/io.py:262-366 (reader state is host-side, executed by the Executor's
io pre-pass — see core/readers.py for the TPU-native design).
"""
from ..core import unique_name
from ..core.framework import default_main_program, default_startup_program

__all__ = ["data", "Send", "Recv", "ListenAndServ", "BlockGuardServ",
           "open_recordio_file", "open_files", "read_file",
           "create_shuffle_reader", "create_double_buffer_reader",
           "create_multi_pass_reader", "shuffle", "double_buffer",
           "multi_pass"]


def data(name, shape, append_batch_size=True, dtype="float32", lod_level=0,
         type=None, stop_gradient=True):
    # reference semantics (layers/io.py:67-75): None becomes -1, and any
    # explicit -1/None in the shape disables batch-dim prepending
    shape = [-1 if s is None else s for s in shape]
    if append_batch_size:
        if all(s >= 0 for s in shape):
            shape = [-1] + shape
        # if user already put a -1 in shape, don't prepend another batch dim
    block = default_main_program().global_block()
    if lod_level > 0:
        # padded-dense sequence layout: [num_seqs, max_len, *feature] plus an
        # int32 lengths companion (SURVEY.md §6.3). The reference feeds a flat
        # [total_tokens, *feature] LoDTensor; the Executor converts.
        shape = [shape[0], -1] + shape[1:]
        seq_len = block.create_var(
            name=name + "@SEQLEN", shape=[-1], dtype="int32",
            stop_gradient=True, is_data=True)
    main = block.create_var(
        name=name, shape=shape, dtype=dtype, lod_level=lod_level,
        stop_gradient=stop_gradient, is_data=True)
    if lod_level > 0:
        main.seq_len_var = name + "@SEQLEN"
    return main


class BlockGuardServ(object):
    """with server.do(): — collect the optimize block, then complete_op
    (parity: reference layers/io.py:87)."""

    def __init__(self, server):
        if not isinstance(server, ListenAndServ):
            raise TypeError("BlockGuardServ takes a ListenAndServ")
        self.server = server
        self.program = default_main_program()

    def __enter__(self):
        self.block = self.program.create_block()
        return self.block

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            self.program.rollback()  # never leave the server block current
            return False
        self.server.complete_op()
        self.program.rollback()
        return False


class ListenAndServ(object):
    """Parity: reference layers/io.py:108 — wraps the listen_and_serv op:
    a server block receiving vars and running the optimize sub-block. On
    TPU there is no RPC loop; the op is the same marker the
    DistributeTranspiler's pserver programs carry, and the collected
    optimize block executes directly (sharded-parameter semantics — see
    transpiler/distribute_transpiler.py)."""

    def __init__(self, endpoint, inputs=None, fan_in=1, optimizer_mode=True):
        self.inputs = list(inputs or [])
        self.endpoint = endpoint
        self.fan_in = fan_in
        self.optimizer_mode = optimizer_mode

    def do(self):
        return BlockGuardServ(self)

    def get_params_and_grads(self):
        prog = default_main_program()
        block = prog.current_block()
        params, grads = [], []
        for op in block.ops:
            if self.optimizer_mode:
                if "Grad" in op.inputs and "Param" in op.inputs:
                    params.append(op.inputs["Param"][0])
                    grads.append(op.inputs["Grad"][0])
            else:
                for names in op.inputs.values():
                    for n in names:
                        params.append(n)
                        grads.append(n)
        return params, grads

    def complete_op(self):
        prog = default_main_program()
        current = prog.current_block()
        parent = prog.blocks[current.parent_idx]
        params, grads = self.get_params_and_grads()
        parent.append_op(
            type="listen_and_serv", inputs={}, outputs={},
            attrs={"endpoint": self.endpoint, "Fanin": self.fan_in,
                   "ParamList": params, "GradList": grads,
                   "sub_block": current.idx},
            infer_shape=False)


def Send(endpoints, send_vars, get_vars=None):
    """Parity: fluid.layers.Send (reference layers/io.py:179) — ship vars
    to parameter servers. Appended as the same 'send' marker op the
    DistributeTranspiler emits; under whole-program GSPMD the actual
    exchange is XLA's reduce-scatter/all-gather over ICI, so the marker
    records placement (endpoints) and lowers to a no-op."""
    assert isinstance(send_vars, list)
    epmap = endpoints.split(",") if isinstance(endpoints, str) \
        else list(endpoints)
    block = default_main_program().current_block()
    block.append_op(
        type="send",
        inputs={"X": [v.name if hasattr(v, "name") else v
                      for v in send_vars]},
        outputs={},
        attrs={"endpoints": epmap, "epmap": {}, "sync_mode": True},
        infer_shape=False)
    return get_vars


def Recv(endpoints, get_vars):
    """Parity: fluid.layers.Recv (reference layers/io.py:207) — fetch vars
    from parameter servers. With sharded parameters living device-side,
    the 'recv' is the identity placement marker (GSPMD all-gathers on
    read), kept so transpiled programs round-trip."""
    assert isinstance(get_vars, list)
    epmap = endpoints.split(",") if isinstance(endpoints, str) \
        else list(endpoints)
    block = default_main_program().current_block()
    names = [v.name if hasattr(v, "name") else v for v in get_vars]
    block.append_op(
        type="recv",
        inputs={},
        outputs={"Out": names},
        attrs={"endpoints": epmap, "epmap": {}},
        infer_shape=False)
    return get_vars


# ---------------------------------------------------------------------------
# in-graph file readers (reference: layers/io.py:262-366). Reader vars are
# persistable; their runtime state is a host-side ReaderState the Executor
# creates/pops in its io pre-pass (core/readers.py).
# ---------------------------------------------------------------------------

def _monkey_patch_reader_methods(reader_var):
    """reader.eof()/reader.reset() operate on the live ReaderState in the
    current scope (parity: monkey_patch_reader_methods, layers/io.py:235)."""
    from ..core.executor import global_scope

    def _state():
        state = global_scope().get(reader_var.name)
        if state is None:
            raise RuntimeError(
                "reader %r has no state; run the startup program first"
                % reader_var.name)
        return state

    reader_var.eof = lambda: _state().eof()
    reader_var.reset = lambda: _state().reset()
    reader_var.stop_gradient = True
    reader_var.persistable = True
    return reader_var


def _create_reader_var(op_type, inputs, attrs, shapes, dtypes, lod_levels):
    # catch the ragged-spec mistake at BUILD time: read_file silently zips
    # the three lists, so a shapes/dtypes length mismatch would truncate
    # reader fields and only surface as a record-arity error mid-training
    if not (len(shapes) == len(dtypes) == len(lod_levels)):
        raise ValueError(
            "%s: shapes (%d), dtypes (%d) and lod_levels (%d) must "
            "describe the same number of reader fields"
            % (op_type, len(shapes), len(dtypes), len(lod_levels)))
    name = unique_name.generate(op_type)
    startup_blk = default_startup_program().current_block()
    startup_var = startup_blk.create_var(name=name, persistable=True,
                                         stop_gradient=True)
    startup_blk.append_op(type=op_type, inputs=inputs,
                          outputs={"Out": [startup_var]}, attrs=attrs,
                          infer_shape=False)
    main_blk = default_main_program().current_block()
    main_var = main_blk.create_var(name=name, persistable=True,
                                   stop_gradient=True)
    main_var.reader_shapes = list(shapes)
    main_var.reader_dtypes = list(dtypes)
    main_var.reader_lod_levels = list(lod_levels)
    return _monkey_patch_reader_methods(main_var)


def open_recordio_file(filename, shapes, lod_levels, dtypes):
    """Reader over one recordio file written by
    fluid.recordio_writer.convert_reader_to_recordio_file
    (reference: layers/io.py:262 + create_recordio_file_reader_op.cc)."""
    return _create_reader_var(
        "create_recordio_file_reader", None,
        {"filename": filename, "shapes": [list(s) for s in shapes],
         "lod_levels": list(lod_levels)},
        shapes, dtypes, lod_levels)


def open_files(filenames, thread_num, shapes, lod_levels, dtypes):
    """Reader over several recordio files scanned by thread_num host
    threads; record order across files is nondeterministic (reference:
    layers/io.py:291 + open_files_op.cc)."""
    return _create_reader_var(
        "open_files", None,
        {"file_names": list(filenames), "thread_num": int(thread_num),
         "shapes": [list(s) for s in shapes],
         "lod_levels": list(lod_levels)},
        shapes, dtypes, lod_levels)


def _decorated_reader(op_type, reader, attrs):
    return _create_reader_var(
        op_type, {"UnderlyingReader": [reader.name]}, attrs,
        getattr(reader, "reader_shapes", []),
        getattr(reader, "reader_dtypes", []),
        getattr(reader, "reader_lod_levels", []))


def create_shuffle_reader(reader, buffer_size, seed=0):
    return _decorated_reader("create_shuffle_reader", reader,
                             {"buffer_size": int(buffer_size), "seed": seed})


def create_double_buffer_reader(reader, place=None, capacity=2):
    attrs = {"capacity": int(capacity)}
    if place is not None:
        attrs["__place__"] = place
    return _decorated_reader("create_double_buffer_reader", reader, attrs)


def create_multi_pass_reader(reader, pass_num):
    return _decorated_reader("create_multi_pass_reader", reader,
                             {"pass_num": int(pass_num)})


# later-fluid spellings of the same decorators
shuffle = create_shuffle_reader
double_buffer = create_double_buffer_reader
multi_pass = create_multi_pass_reader


def read_file(file_obj):
    """Pop one record from a reader: returns one Variable per reader field
    (reference: layers/io.py:353). Executed by the Executor's io pre-pass —
    the popped arrays enter the jitted program as feeds. Raises
    fluid.core.readers.EOFException at run time when exhausted; check
    reader.eof() first (the reference's pattern: `while not reader.eof()`)."""
    block = default_main_program().current_block()
    shapes = getattr(file_obj, "reader_shapes", None)
    if not shapes:
        raise ValueError("read_file needs a reader variable from "
                         "open_recordio_file/open_files or a decorator")
    dtypes = file_obj.reader_dtypes
    lod_levels = file_obj.reader_lod_levels
    outs = []
    for shape, dtype, lod in zip(shapes, dtypes, lod_levels):
        outs.append(block.create_var(
            name=unique_name.generate("read_file"),
            shape=[int(s) for s in list(shape)],  # shapes include batch dim
            dtype=dtype, lod_level=lod, stop_gradient=True, is_data=True))
    block.append_op(type="read", inputs={"Reader": [file_obj.name]},
                    outputs={"Out": outs}, infer_shape=False)
    return outs[0] if len(outs) == 1 else outs
