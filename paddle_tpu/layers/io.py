"""Data-input layers.

Parity: python/paddle/fluid/layers/io.py — `data` declares a feed Variable
(batch dim prepended as -1, like the reference's append_batch_size).
"""
from ..core.framework import default_main_program, default_startup_program

__all__ = ["data"]


def data(name, shape, append_batch_size=True, dtype="float32", lod_level=0,
         type=None, stop_gradient=True):
    shape = list(shape)
    if append_batch_size:
        if all(s >= 0 for s in shape):
            shape = [-1] + shape
        # if user already put a -1 in shape, don't prepend another batch dim
    block = default_main_program().global_block()
    if lod_level > 0:
        # padded-dense sequence layout: [num_seqs, max_len, *feature] plus an
        # int32 lengths companion (SURVEY.md §6.3). The reference feeds a flat
        # [total_tokens, *feature] LoDTensor; the Executor converts.
        shape = [shape[0], -1] + shape[1:]
        seq_len = block.create_var(
            name=name + "@SEQLEN", shape=[-1], dtype="int32",
            stop_gradient=True, is_data=True)
    main = block.create_var(
        name=name, shape=shape, dtype=dtype, lod_level=lod_level,
        stop_gradient=stop_gradient, is_data=True)
    if lod_level > 0:
        main.seq_len_var = name + "@SEQLEN"
    return main
