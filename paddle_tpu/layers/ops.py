"""Auto-generated thin layer wrappers over registered ops.

Parity: python/paddle/fluid/layers/ops.py + layer_function_generator.py —
the reference generates these from OpProto; here they are generated from a
slot-spec table. Both calling styles work: `mean(x)` and `mean(x=var)`.
"""
from ..core.framework import Variable
from ..core.layer_helper import LayerHelper

__activations__ = [
    "sigmoid", "logsigmoid", "exp", "relu", "tanh", "tanh_shrink",
    "softshrink", "sqrt", "abs", "ceil", "floor", "cos", "sin", "round",
    "reciprocal", "log", "square", "softplus", "softsign", "brelu",
    "leaky_relu", "soft_relu", "elu", "relu6", "pow", "stanh", "hard_shrink",
    "thresholded_relu", "hard_sigmoid", "swish",
]

__all__ = [
    "mean", "mul", "reshape", "scale", "sigmoid_cross_entropy_with_logits",
    "elementwise_add", "elementwise_div", "elementwise_sub", "elementwise_mul",
    "elementwise_max", "elementwise_min", "elementwise_pow", "clip",
    "clip_by_norm", "logical_and", "logical_or", "logical_xor", "logical_not",
    "uniform_random", "uniform_random_batch_size_like", "gaussian_random",
    "gaussian_random_batch_size_like", "cumsum", "scatter", "sum", "gather",
    "fill_constant_batch_size_like", "squeeze", "unsqueeze",
    "generate_layer_fn", "autodoc", "deprecated",
] + __activations__

# op type -> (input slots [(slot, kw, required)], output slots, out dtype fn)
_UNARY = [("X", "x", True)]
_BINARY = [("X", "x", True), ("Y", "y", True)]

_SPECS = {
    "mean": (_UNARY, ["Out"]),
    "mul": (_BINARY, ["Out"]),
    "reshape": (_UNARY, ["Out"]),
    "scale": (_UNARY, ["Out"]),
    "sigmoid_cross_entropy_with_logits":
        ([("X", "x", True), ("Label", "label", True)], ["Out"]),
    "clip": (_UNARY, ["Out"]),
    "clip_by_norm": (_UNARY, ["Out"]),
    "logical_not": (_UNARY, ["Out"]),
    "cumsum": (_UNARY, ["Out"]),
    "scatter": ([("X", "x", True), ("Ids", "ids", True),
                 ("Updates", "updates", True)], ["Out"]),
    "gather": ([("X", "x", True), ("Index", "index", True)], ["Out"]),
    "sum": ([("X", "x", True)], ["Out"]),
    "uniform_random": ([], ["Out"]),
    "gaussian_random": ([], ["Out"]),
    "uniform_random_batch_size_like": ([("Input", "input", True)], ["Out"]),
    "gaussian_random_batch_size_like": ([("Input", "input", True)], ["Out"]),
    "fill_constant_batch_size_like": ([("Input", "input", True)], ["Out"]),
    "squeeze": (_UNARY, ["Out"]),
    "unsqueeze": (_UNARY, ["Out"]),
}
for _a in __activations__:
    _SPECS[_a] = (_UNARY, ["Out"])
for _e in ["elementwise_add", "elementwise_div", "elementwise_sub",
           "elementwise_mul", "elementwise_max", "elementwise_min",
           "elementwise_pow"]:
    _SPECS[_e] = (_BINARY, ["Out"])
for _l in ["logical_and", "logical_or", "logical_xor"]:
    _SPECS[_l] = (_BINARY, ["Out"])


def generate_layer_fn(op_type):
    in_slots, out_slots = _SPECS[op_type]

    def layer_fn(*args, **kwargs):
        name = kwargs.pop("name", None)
        act = kwargs.pop("act", None)
        inputs = {}
        pos = list(args)
        dtype = kwargs.get("dtype")  # stays in kwargs → reaches op attrs too
        for slot, kw, required in in_slots:
            v = kwargs.pop(kw, None)
            if v is None and pos:
                v = pos.pop(0)
            if v is None:
                if required:
                    raise ValueError("%s missing input %r" % (op_type, kw))
                continue
            inputs[slot] = v if isinstance(v, (list, tuple)) else [v]
            if dtype is None:
                first = inputs[slot][0]
                if isinstance(first, Variable):
                    dtype = first.dtype
        helper = LayerHelper(op_type, name=name, act=act)
        outs = {s: [helper.create_variable_for_type_inference(
            dtype or "float32")] for s in out_slots}
        helper.append_op(type=op_type, inputs=inputs, outputs=outs,
                         attrs=kwargs)
        out = outs[out_slots[0]][0]
        return helper.append_activation(out)

    layer_fn.__name__ = op_type
    return layer_fn


def autodoc(comment=""):
    """Decorator stamping a generated docstring (parity:
    layer_function_generator.autodoc)."""
    def _decorator(func):
        func.__doc__ = "%s\nlayer %s: inputs %s" % (
            comment, func.__name__,
            ", ".join(kw for _, kw, _r in
                      _SPECS.get(func.__name__, ([], []))[0]))
        return func
    return _decorator


def deprecated(since="", instead=""):
    """Decorator warning on use (parity: the reference's @deprecated)."""
    import functools
    import warnings

    def _decorator(func):
        @functools.wraps(func)
        def _wrapper(*args, **kwargs):
            warnings.warn(
                "%s is deprecated%s%s" % (
                    func.__name__,
                    (" since %s" % since) if since else "",
                    ("; use %s instead" % instead) if instead else ""),
                DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)
        return _wrapper
    return _decorator


for _op in _SPECS:
    globals()[_op] = generate_layer_fn(_op)
