"""High-level NN layers that build graph ops.

Parity: python/paddle/fluid/layers/nn.py — same function names, argument
names, and op-emission behavior (fc emits mul+sum+bias+act, conv2d creates
its filter parameter, batch_norm creates scale/bias/moving stats, ...).
"""
import numpy as np

from ..core.framework import Variable
from ..core.layer_helper import LayerHelper
from ..core.initializer import ConstantInitializer, NormalInitializer
from ..core.param_attr import ParamAttr
from ..core import unique_name
from ..core.utils import pair as _pair

__all__ = [
    "fc", "embedding", "conv2d", "conv2d_transpose", "pool2d", "batch_norm",
    "layer_norm", "dropout", "softmax", "softmax_with_cross_entropy",
    "cross_entropy", "square_error_cost", "accuracy", "topk", "matmul",
    "one_hot", "reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
    "reduce_prod", "split", "l2_normalize", "cos_sim", "dropout",
    "smooth_l1", "autoincreased_step_counter", "transpose", "im2sequence",
    "multiplex", "label_smooth", "nce", "lrn", "maxout", "relu", "log",
    "expand", "sequence_mask", "linear_chain_crf", "crf_decoding",
    "chunk_eval", "warpctc", "ctc_greedy_decoder", "sequence_erase",
    "edit_distance", "fused_attention",
]


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, is_test=False, name=None, use_mkldnn=False):
    """Fully connected. Parity: fluid.layers.fc (nn.py:88 in reference).

    Emits one mul op per input + sum (if multiple) + bias + activation.
    """
    helper = LayerHelper("fc", **locals())
    dtype = helper.input_dtype()

    mul_results = []
    for input_var, param_attr in helper.iter_inputs_and_params():
        input_shape = input_var.shape
        flatten = num_flatten_dims
        if input_var.lod_level > 0 and num_flatten_dims == 1:
            # sequence input in padded [B, T, D] layout: the reference's flat
            # [total_tokens, D] fc is a per-timestep projection here
            flatten = len(input_shape) - 1
        param_shape = [
            int(np.prod(input_shape[flatten:]))
        ] + [size]
        w = helper.create_parameter(
            attr=param_attr, shape=param_shape, dtype=dtype, is_bias=False)
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="mul",
            inputs={"X": [input_var], "Y": [w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": flatten, "y_num_col_dims": 1})
        mul_results.append(tmp)

    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        helper.append_op(type="sum", inputs={"X": mul_results},
                         outputs={"Out": [pre_bias]})
    pre_activation = helper.append_bias_op(pre_bias, dim_start=flatten)
    return helper.append_activation(pre_activation)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """Parity: fluid.layers.embedding → lookup_table op. `is_sparse` selects
    the reference's SelectedRows grad path; on TPU gathers/scatter-adds are
    already sparse-efficient XLA HLO, so it's accepted and ignored."""
    helper = LayerHelper("embedding", **locals())
    w = helper.create_parameter(
        attr=helper.param_attr, shape=size, dtype=dtype, is_bias=False)
    tmp = helper.create_variable_for_type_inference(dtype)
    padding_idx = -1 if padding_idx is None else \
        padding_idx if padding_idx >= 0 else (size[0] + padding_idx)
    helper.append_op(
        type="lookup_table",
        inputs={"W": [w], "Ids": [input]},
        outputs={"Out": [tmp]},
        attrs={"is_sparse": is_sparse, "is_distributed": is_distributed,
               "padding_idx": padding_idx})
    return tmp


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           use_mkldnn=False, act=None, name=None):
    """Parity: fluid.layers.conv2d (cuDNN kernel → XLA conv on MXU)."""
    helper = LayerHelper("conv2d", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1
    if num_channels % groups != 0:
        raise ValueError("num_channels must be divisible by groups")
    num_filter_channels = num_channels // groups

    filter_size = _pair(filter_size)
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)

    filter_shape = [num_filters, num_filter_channels] + list(filter_size)

    def _get_default_param_initializer():
        std = (2.0 / (filter_size[0] ** 2 * num_channels)) ** 0.5
        return NormalInitializer(0.0, std, 0)

    filter_param = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=_get_default_param_initializer())

    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv2d",
        inputs={"Input": [input], "Filter": [filter_param]},
        outputs={"Output": [pre_bias]},
        attrs={"strides": list(stride), "paddings": list(padding),
               "dilations": list(dilation), "groups": groups,
               "use_cudnn": use_cudnn})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, param_attr=None,
                     bias_attr=None, use_cudnn=True, act=None, name=None):
    """Parity: fluid.layers.conv2d_transpose."""
    helper = LayerHelper("conv2d_transpose", **locals())
    dtype = helper.input_dtype()
    input_channel = input.shape[1]
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    if filter_size is None:
        if output_size is None:
            raise ValueError("output_size must be set when filter_size is None")
        output_size = _pair(output_size)
        h_in, w_in = input.shape[2], input.shape[3]
        filter_size_h = (output_size[0] - (h_in - 1) * stride[0] +
                         2 * padding[0] - 1) // dilation[0] + 1
        filter_size_w = (output_size[1] - (w_in - 1) * stride[1] +
                         2 * padding[1] - 1) // dilation[1] + 1
        filter_size = [filter_size_h, filter_size_w]
    else:
        filter_size = _pair(filter_size)
    filter_shape = [input_channel, num_filters] + list(filter_size)
    img_filter = helper.create_parameter(
        dtype=dtype, shape=filter_shape, attr=helper.param_attr)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv2d_transpose",
        inputs={"Input": [input], "Filter": [img_filter]},
        outputs={"Output": [pre_bias]},
        attrs={"strides": list(stride), "paddings": list(padding),
               "dilations": list(dilation)})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, use_mkldnn=False, name=None):
    """Parity: fluid.layers.pool2d."""
    if pool_type not in ("max", "avg"):
        raise ValueError("pool_type must be 'max' or 'avg'")
    helper = LayerHelper("pool2d", **locals())
    dtype = helper.input_dtype()
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": list(_pair(pool_size)),
               "global_pooling": global_pooling,
               "strides": list(_pair(pool_stride)),
               "paddings": list(_pair(pool_padding)),
               "ceil_mode": ceil_mode})
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, use_mkldnn=False, name=None,
               moving_mean_name=None, moving_variance_name=None,
               do_model_average_for_mean_and_var=False):
    """Parity: fluid.layers.batch_norm — creates scale/bias params and
    persistable moving mean/variance updated in-place each step."""
    helper = LayerHelper("batch_norm", **locals())
    dtype = helper.input_dtype()
    input_shape = input.shape
    if data_layout == "NCHW":
        channel_num = input_shape[1] if len(input_shape) > 2 else input_shape[-1]
    else:
        channel_num = input_shape[-1]
    param_shape = [channel_num]

    scale = helper.create_parameter(
        attr=helper.param_attr, shape=param_shape, dtype=dtype,
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=param_shape, dtype=dtype, is_bias=True)

    mean = helper.create_parameter(
        attr=ParamAttr(name=moving_mean_name,
                       initializer=ConstantInitializer(0.0), trainable=False),
        shape=param_shape, dtype=dtype)
    mean.stop_gradient = True
    variance = helper.create_parameter(
        attr=ParamAttr(name=moving_variance_name,
                       initializer=ConstantInitializer(1.0), trainable=False),
        shape=param_shape, dtype=dtype)
    variance.stop_gradient = True

    saved_mean = helper.create_variable_for_type_inference(
        dtype=dtype, stop_gradient=True)
    saved_variance = helper.create_variable_for_type_inference(
        dtype=dtype, stop_gradient=True)
    # in_place is accepted for API parity but always materializes a fresh
    # var: aliasing Y onto X would make the vjp backward replay read the
    # normalized output as its input (XLA buffer reuse makes the "in place"
    # memory saving moot anyway).
    batch_norm_out = helper.create_variable_for_type_inference(dtype)

    helper.append_op(
        type="batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                "Mean": [mean], "Variance": [variance]},
        outputs={"Y": [batch_norm_out], "MeanOut": [mean],
                 "VarianceOut": [variance], "SavedMean": [saved_mean],
                 "SavedVariance": [saved_variance]},
        attrs={"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
               "data_layout": data_layout})
    return helper.append_activation(batch_norm_out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    """Parity: fluid.layers.layer_norm."""
    helper = LayerHelper("layer_norm", **locals())
    dtype = helper.input_dtype()
    input_shape = input.shape
    param_shape = [int(np.prod(input_shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            attr=helper.param_attr, shape=param_shape, dtype=dtype,
            default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=param_shape, dtype=dtype, is_bias=True)
        inputs["Bias"] = [b]
    mean_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="layer_norm", inputs=inputs,
        outputs={"Y": [out], "Mean": [mean_out], "Variance": [var_out]},
        attrs={"epsilon": epsilon, "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(out)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None):
    """Parity: fluid.layers.dropout (Mask output, downgrade_in_infer)."""
    helper = LayerHelper("dropout", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        type="dropout",
        inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={"dropout_prob": dropout_prob, "is_test": is_test,
               "seed": seed if seed is not None else 0})
    return out


def softmax(input, use_cudnn=True, name=None):
    helper = LayerHelper("softmax", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="softmax", inputs={"X": [input]},
                     outputs={"Out": [out]})
    return out


def relu(x, name=None):
    helper = LayerHelper("relu", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="relu", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def log(x, name=None):
    helper = LayerHelper("log", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="log", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def cross_entropy(input, label, soft_label=False):
    """Parity: fluid.layers.cross_entropy (input = probabilities)."""
    helper = LayerHelper("cross_entropy", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="cross_entropy",
        inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
        attrs={"soft_label": soft_label})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False):
    """Parity: fluid.layers.softmax_with_cross_entropy (fused, numerically
    stable; single XLA fusion on TPU)."""
    helper = LayerHelper("softmax_with_cross_entropy", **locals())
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Softmax": [softmax_out], "Loss": [loss]},
        attrs={"soft_label": soft_label})
    return loss


def square_error_cost(input, label):
    """Parity: fluid.layers.square_error_cost."""
    helper = LayerHelper("square_error_cost", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="square_error_cost",
        inputs={"X": [input], "Y": [label]},
        outputs={"Out": [out]})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1", **locals())
    diff = helper.create_variable_for_type_inference(x.dtype)
    loss = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op(
        type="smooth_l1_loss", inputs=inputs,
        outputs={"Diff": [diff], "Out": [loss]},
        attrs={"sigma": sigma if sigma is not None else 1.0})
    return loss


def accuracy(input, label, k=1, correct=None, total=None):
    """Parity: fluid.layers.accuracy (emits topk + accuracy ops)."""
    helper = LayerHelper("accuracy", **locals())
    topk_out = helper.create_variable_for_type_inference(input.dtype)
    topk_indices = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="topk", inputs={"X": [input]},
        outputs={"Out": [topk_out], "Indices": [topk_indices]},
        attrs={"k": k})
    acc_out = helper.create_variable_for_type_inference("float32")
    if correct is None:
        correct = helper.create_variable_for_type_inference("int32")
    if total is None:
        total = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices],
                "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct],
                 "Total": [total]})
    return acc_out


def topk(input, k):
    helper = LayerHelper("topk", **locals())
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="topk", inputs={"X": [input]},
                     outputs={"Out": [values], "Indices": [indices]},
                     attrs={"k": k})
    return values, indices


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    helper = LayerHelper("matmul", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="matmul", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]},
        attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y})
    return out


def one_hot(input, depth):
    helper = LayerHelper("one_hot", **locals())
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(type="one_hot", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"depth": depth})
    return out


def _reduce_layer(op_type):
    def layer(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, **locals())
        out = helper.create_variable_for_type_inference(input.dtype)
        attrs = {"keep_dim": keep_dim,
                 "reduce_all": dim is None,
                 "dim": dim if dim is not None else 0}
        helper.append_op(type=op_type, inputs={"X": [input]},
                         outputs={"Out": [out]}, attrs=attrs)
        return out
    layer.__name__ = op_type
    return layer


reduce_sum = _reduce_layer("reduce_sum")
reduce_mean = _reduce_layer("reduce_mean")
reduce_max = _reduce_layer("reduce_max")
reduce_min = _reduce_layer("reduce_min")
reduce_prod = _reduce_layer("reduce_prod")


def split(input, num_or_sections, dim=-1, name=None):
    """Parity: fluid.layers.split."""
    helper = LayerHelper("split", **locals())
    input_shape = input.shape
    dim = dim if dim >= 0 else dim + len(input_shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        attrs = {"num": num, "sections": [], "axis": dim}
    else:
        num = len(num_or_sections)
        attrs = {"num": 0, "sections": list(num_or_sections), "axis": dim}
    outs = [helper.create_variable_for_type_inference(input.dtype)
            for _ in range(num)]
    helper.append_op(type="split", inputs={"X": [input]},
                     outputs={"Out": outs}, attrs=attrs)
    return outs


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="l2_normalize", inputs={"X": [x]},
                     outputs={"Out": [out], "Norm": [norm]},
                     attrs={"axis": axis, "epsilon": epsilon})
    return out


def cos_sim(X, Y):
    helper = LayerHelper("cos_sim", **locals())
    out = helper.create_variable_for_type_inference(X.dtype)
    xnorm = helper.create_variable_for_type_inference(X.dtype)
    ynorm = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op(type="cos_sim", inputs={"X": [X], "Y": [Y]},
                     outputs={"Out": [out], "XNorm": [xnorm],
                              "YNorm": [ynorm]})
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="transpose", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": list(perm)})
    return out


def multiplex(inputs, index):
    helper = LayerHelper("multiplex", **locals())
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op(type="multiplex",
                     inputs={"X": inputs, "Ids": [index]},
                     outputs={"Out": [out]})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", **locals())
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    helper.append_op(type="label_smooth", inputs=inputs,
                     outputs={"Out": [out]}, attrs={"epsilon": float(epsilon)})
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    mid = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="lrn", inputs={"X": [input]},
                     outputs={"Out": [out], "MidOut": [mid]},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="maxout", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"groups": groups})
    return out


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None):
    """Parity: fluid.layers.nce."""
    helper = LayerHelper("nce", **locals())
    dim = input.shape[1]
    num_neg_samples = 10 if num_neg_samples is None else int(num_neg_samples)
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[num_total_classes, dim],
        dtype=input.dtype, is_bias=False)
    b = helper.create_parameter(
        attr=helper.bias_attr, shape=[num_total_classes, 1],
        dtype=input.dtype, is_bias=True)
    cost = helper.create_variable_for_type_inference(input.dtype)
    sample_logits = helper.create_variable_for_type_inference(input.dtype)
    sample_labels = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="nce",
        inputs={"Input": [input], "Label": [label], "Weight": [w],
                "Bias": [b]},
        outputs={"Cost": [cost], "SampleLogits": [sample_logits],
                 "SampleLabels": [sample_labels]},
        attrs={"num_total_classes": int(num_total_classes),
               "num_neg_samples": num_neg_samples})
    return cost / (num_neg_samples + 1)


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    """Parity: fluid.layers.im2sequence (OCR path). Output is a sequence:
    one timestep per output pixel, feature = C*kh*kw patch."""
    helper = LayerHelper("im2sequence", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out_len = helper.block.create_var(
        name=out.name + "@SEQLEN", shape=[-1], dtype="int32",
        stop_gradient=True)
    helper.append_op(
        type="im2sequence", inputs={"X": [input]},
        outputs={"Out": [out], "OutLen": [out_len]},
        attrs={"kernels": list(_pair(filter_size)),
               "strides": list(_pair(stride)),
               "paddings": list(_pair(padding)) * 2})
    out.lod_level = 1
    out.seq_len_var = out_len.name
    return out


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Parity: fluid.layers.autoincreased_step_counter — a persistable int64
    counter incremented once per run; drives LR schedules."""
    helper = LayerHelper("global_step_counter")
    counter_name = counter_name or "@STEP_COUNTER@"
    counter = helper.create_or_get_global_variable(
        name=counter_name, dtype="int64", shape=[1], persistable=True)
    if counter.op is None:
        helper.set_variable_initializer(
            counter, initializer=ConstantInitializer(value=begin - 1))
        counter.op = helper.main_program.global_block().prepend_op(
            type="increment",
            inputs={"X": [counter]},
            outputs={"Out": [counter]},
            attrs={"step": float(step)},
            infer_shape=False)
        counter.stop_gradient = True
    return counter




def fused_attention(q, k, v, causal=False, scale=None, kv_len=None,
                    block_q=None, block_k=None, sp_impl="ring", name=None):
    """Flash attention over [B, T, H, D] q/k/v (TPU-native addition — the
    reference era built attention from matmul+softmax ops; this is the
    fused pallas path, see ops/pallas_kernels.py). kv_len: optional [B]
    int32 Variable of true key lengths (padded-batch masking + block
    skipping); defaults to k's sequence-lengths companion when k is a
    lod_level>0 sequence. Under a ParallelExecutor mesh with an 'sp'
    axis the op runs sequence-parallel; sp_impl chooses the algorithm:
    "ring" (K/V rotation over ICI, any head count) or "ulysses"
    (all-to-all head sharding, needs heads % sp == 0)."""
    if sp_impl not in ("ring", "ulysses"):
        raise ValueError(
            "fused_attention sp_impl must be 'ring' or 'ulysses', got %r"
            % (sp_impl,))
    helper = LayerHelper("fused_attention", **locals())
    out = helper.create_variable_for_type_inference(q.dtype)
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if kv_len is None and getattr(k, "seq_len_var", None):
        kv_len = k.block.var_recursive(k.seq_len_var)
    if kv_len is not None:
        inputs["KVLen"] = [kv_len]
    helper.append_op(
        type="fused_attention", inputs=inputs,
        outputs={"Out": [out]},
        attrs={"causal": bool(causal),
               "scale": None if scale is None else float(scale),
               # None = unpinned: the trace-time dispatch resolves tiles
               # from kernel_config (per-shape tuned table; defaults =
               # the old 128/128 literals). An explicit int here pins.
               "block_q": None if block_q is None else int(block_q),
               "block_k": None if block_k is None else int(block_k),
               "sp_impl": str(sp_impl)})
    if q.shape is not None:
        out.shape = tuple(q.shape)
    return out


def expand(x, expand_times, name=None):
    """Tile x along each dim. Parity: fluid.layers.expand / expand_op.cc."""
    helper = LayerHelper("expand", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="expand", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"expand_times": list(expand_times)})
    return out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """[N] lengths -> [N, maxlen] 0/1 mask. Parity: fluid.layers.sequence_mask
    / sequence_mask_op.h. `maxlen` may be an int or a Variable whose dim 1
    supplies the static length (TPU needs a static bound)."""
    helper = LayerHelper("sequence_mask", **locals())
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [x]}
    attrs = {"out_dtype": dtype}
    if isinstance(maxlen, Variable):
        inputs["MaxLenRef"] = [maxlen]
    elif maxlen is not None:
        attrs["maxlen"] = int(maxlen)
    else:
        raise ValueError("TPU sequence_mask needs a static maxlen (int or a "
                         "Variable whose second dim provides it)")
    helper.append_op(type="sequence_mask", inputs=inputs,
                     outputs={"Y": [out]}, attrs=attrs, infer_shape=False)
    if isinstance(maxlen, Variable):
        m = maxlen.shape[1] if maxlen.shape is not None else -1
    else:
        m = int(maxlen)
    if x.shape is not None:
        out.shape = (x.shape[0], m)
    out.stop_gradient = True
    return out


def _crf_seq_len(helper, x):
    from .sequence import _seq_len
    return _seq_len(helper, x)


def linear_chain_crf(input, label, param_attr=None):
    """Linear-chain CRF negative log-likelihood, one cost per sequence.

    Parity: fluid.layers.linear_chain_crf (reference nn.py:786) over
    linear_chain_crf_op.h. Creates the [size+2, size] transition parameter
    (row 0 start, row 1 end, rows 2.. tag->tag); returns LogLikelihood
    [num_seqs, 1]. The reference's Alpha/EmissionExps/TransitionExps
    outputs existed only to feed the hand-written grad kernel and have no
    equivalent here (jax.vjp re-derives the backward pass).
    """
    helper = LayerHelper("linear_chain_crf", **locals())
    size = input.shape[-1]
    transition = helper.create_parameter(
        attr=helper.param_attr, shape=[size + 2, size],
        dtype=helper.input_dtype())
    ll = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op(
        type="linear_chain_crf",
        inputs={"Emission": [input], "Transition": [transition],
                "Label": [label], "XLen": [_crf_seq_len(helper, input)]},
        outputs={"LogLikelihood": [ll]})
    ll.lod_level = 0
    ll.seq_len_var = None
    ll.shape = (-1, 1)
    return ll


def crf_decoding(input, param_attr, label=None):
    """Viterbi decode with the trained CRF transitions.

    Parity: fluid.layers.crf_decoding (reference nn.py:812) over
    crf_decoding_op.h. Without label: the best tag path (sequence, int64).
    With label: per-token 1/0 correctness indicators.
    """
    helper = LayerHelper("crf_decoding", **locals())
    size = input.shape[-1]
    transition = helper.create_parameter(
        attr=helper.param_attr, shape=[size + 2, size],
        dtype=helper.input_dtype())
    path = helper.create_variable_for_type_inference("int64")
    inputs = {"Emission": [input], "Transition": [transition],
              "XLen": [_crf_seq_len(helper, input)]}
    if label is not None:
        inputs["Label"] = [label]
    helper.append_op(type="crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": [path]})
    path.stop_gradient = True
    return path


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None):
    """Chunk-level precision/recall/F1 (IOB/IOE/IOBES/plain schemes).

    Parity: fluid.layers.chunk_eval (reference nn.py:1014) over
    chunk_eval_op.h; label encodes (chunk_type, tag) as
    chunk_type * num_tag_types + tag.
    """
    helper = LayerHelper("chunk_eval", **locals())
    precision = helper.create_variable_for_type_inference("float32")
    recall = helper.create_variable_for_type_inference("float32")
    f1_score = helper.create_variable_for_type_inference("float32")
    num_infer = helper.create_variable_for_type_inference("int64")
    num_label = helper.create_variable_for_type_inference("int64")
    num_correct = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="chunk_eval",
        inputs={"Inference": [input], "Label": [label],
                "XLen": [_crf_seq_len(helper, input)]},
        outputs={"Precision": [precision], "Recall": [recall],
                 "F1-Score": [f1_score], "NumInferChunks": [num_infer],
                 "NumLabelChunks": [num_label],
                 "NumCorrectChunks": [num_correct]},
        attrs={"num_chunk_types": num_chunk_types,
               "chunk_scheme": chunk_scheme,
               "excluded_chunk_types": excluded_chunk_types or []})
    for v in (precision, recall, f1_score, num_infer, num_label, num_correct):
        v.lod_level = 0
        v.seq_len_var = None
        v.shape = (1,)
        v.stop_gradient = True
    return (precision, recall, f1_score, num_infer, num_label, num_correct)


def warpctc(input, label, blank=0, norm_by_times=False):
    """CTC loss on unnormalized logit sequences, one loss per sequence.

    Parity: fluid.layers.warpctc (reference nn.py:2620) over warpctc_op;
    the warp-ctc library's internal softmax is part of the op. Returns
    Loss [num_seqs, 1].
    """
    helper = LayerHelper("warpctc", **locals())
    loss_out = helper.create_variable_for_type_inference(input.dtype)
    grad_out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="warpctc",
        inputs={"Logits": [input], "Label": [label],
                "XLen": [_crf_seq_len(helper, input)],
                "LabelLen": [_crf_seq_len(helper, label)]},
        outputs={"Loss": [loss_out], "WarpCTCGrad": [grad_out]},
        attrs={"blank": blank, "norm_by_times": norm_by_times})
    loss_out.lod_level = 0
    loss_out.seq_len_var = None
    loss_out.shape = (-1, 1)
    return loss_out


def _erase_or_align_out(helper, op_type, inputs, attrs, dtype="int64"):
    """Emit an op that compacts sequences (new data + new lengths)."""
    out = helper.create_variable_for_type_inference(dtype)
    out_len = helper.block.create_var(
        name=out.name + "@SEQLEN", shape=[-1], dtype="int32",
        stop_gradient=True)
    out_slot = "Output" if op_type == "ctc_align" else "Out"
    helper.append_op(
        type=op_type, inputs=inputs,
        outputs={out_slot: [out], "OutLen": [out_len]}, attrs=attrs,
        infer_shape=False)
    out.lod_level = 1
    out.seq_len_var = out_len.name
    out.stop_gradient = True
    return out


def ctc_greedy_decoder(input, blank, name=None):
    """Greedy CTC decode: argmax per step, merge repeats, drop blanks.

    Parity: fluid.layers.ctc_greedy_decoder (reference nn.py:2478):
    top_k(k=1) + ctc_align(merge_repeated=True).
    """
    helper = LayerHelper("ctc_greedy_decoder", **locals())
    topk_out = helper.create_variable_for_type_inference(input.dtype)
    topk_indices = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="topk", inputs={"X": [input]},
        outputs={"Out": [topk_out], "Indices": [topk_indices]},
        attrs={"k": 1})
    out = _erase_or_align_out(
        helper, "ctc_align",
        {"Input": [topk_indices], "XLen": [_crf_seq_len(helper, input)]},
        {"merge_repeated": True, "blank": blank})
    if input.shape is not None:
        out.shape = (input.shape[0], input.shape[1])
    return out


def sequence_erase(input, tokens):
    """Remove the given token ids from each sequence (compacting it).

    Parity: sequence_erase_op (used by edit_distance's ignored_tokens)."""
    helper = LayerHelper("sequence_erase", **locals())
    out = _erase_or_align_out(
        helper, "sequence_erase",
        {"X": [input], "XLen": [_crf_seq_len(helper, input)]},
        {"tokens": list(tokens)})
    if input.shape is not None:
        out.shape = tuple(input.shape[:2])
    return out


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  name=None):
    """Levenshtein distance between hypothesis and reference sequences.

    Parity: fluid.layers.edit_distance (reference nn.py:2532). Returns
    (distances [num_seqs, 1] float32, sequence_num [1] int64).
    """
    helper = LayerHelper("edit_distance", **locals())
    if ignored_tokens:
        input = sequence_erase(input, ignored_tokens)
        label = sequence_erase(label, ignored_tokens)
    out = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="edit_distance",
        inputs={"Hyps": [input], "Refs": [label],
                "HypsLen": [_crf_seq_len(helper, input)],
                "RefsLen": [_crf_seq_len(helper, label)]},
        outputs={"Out": [out], "SequenceNum": [seq_num]},
        attrs={"normalized": normalized})
    for v in (out, seq_num):
        v.lod_level = 0
        v.seq_len_var = None
        v.stop_gradient = True
    out.shape = (-1, 1)
    seq_num.shape = (1,)
    return out, seq_num
