"""Fluid layers for the parallel subsystems: pipelined_stack (PP) and
switch_moe (EP).

These are the Program-path entries to parallel/pipeline.py and
parallel/moe.py: build the model with them like any other layer, train it
with Executor on one chip (sequential / dense semantics), and hand the
same Program to ParallelExecutor with a mesh carrying a 'pp' / 'ep' axis
to get the GPipe looped-pipeline schedule / the GShard-style expert
all-to-all — no model rewrite. The reference era (mozga-intel/Paddle,
2018) predates both; its only partitioning is the pserver parameter split
(python/paddle/fluid/distribute_transpiler.py).
"""
from ..core.framework import Variable
from ..core.layer_helper import LayerHelper
from ..core.param_attr import ParamAttr
from ..core import unique_name

__all__ = ["pipelined_stack", "switch_moe"]


def _freeze(v):
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    return v


def _block_sig(program, block, canon=None):
    """Structural signature of a stage sub-block: op types, attrs, AND
    dataflow wiring (recursing into nested sub-blocks, whose indices
    differ per stage even when their contents match). Execution always
    uses stage 0's template, so ANY divergence across stages — attrs
    (fc(act='relu') vs 'tanh') or topology (fc(fc(x)) vs fc(x)) — must be
    a build error, not silent stage-0 math. Wiring is compared through
    first-seen canonical ids, so the generated var names themselves may
    legitimately differ per stage."""
    canon = {} if canon is None else canon

    def cid(n):
        if n not in canon:
            canon[n] = len(canon)
        return canon[n]

    sig = []
    for op in block.ops:
        attrs = []
        for k in sorted(op.attrs):
            if k == "sub_block":
                idx = op.attrs[k]
                attrs.append((k, _block_sig(program, program.blocks[idx],
                                            canon)))
            elif k.endswith(("_name", "_names")):
                # binding metadata holds per-stage generated var names
                # (rnn_scan in_names, conditional out_names, ...); their
                # wiring is canonicalized like op input/output names
                v = op.attrs[k]
                names = v if isinstance(v, (list, tuple)) else [v]
                attrs.append((k, tuple(cid(x) for x in names
                                       if isinstance(x, str))))
            else:
                attrs.append((k, _freeze(op.attrs[k])))
        wiring = tuple(
            (kind, slot, tuple(cid(n) for n in names if n))
            for kind, slots in (("in", op.inputs), ("out", op.outputs))
            for slot, names in sorted(slots.items()))
        sig.append((op.type, tuple(attrs), wiring))
    return tuple(sig)


def _check_stage_block(program, blk, avail, s):
    """Validate one stage sub-block (recursively): every read resolves
    inside the stage, and nothing writes persistable state. Nested
    sub-block lowerings bind their own placeholder names via *_name(s)
    attrs (rnn_scan in_names/pre_names/..., conditional out_names);
    those count as available inside the nested block."""
    for op in blk.ops:
        for n in op.all_input_vars():
            if n and n not in avail:
                raise ValueError(
                    "pipeline stage %d op %r reads %r from outside the "
                    "stage; stages must be self-contained (only their "
                    "own parameters and the stage input)" % (s, op.type, n))
        bound = set()
        for k, v in op.attrs.items():
            if k.endswith("_names") and isinstance(v, (list, tuple)):
                bound.update(x for x in v if isinstance(x, str))
            elif k.endswith("_name") and isinstance(v, str):
                bound.add(v)
        idx = op.attrs.get("sub_block")
        if isinstance(idx, int):
            _check_stage_block(program, program.blocks[idx],
                               avail | bound, s)
        for n in op.all_output_vars():
            if not n:
                continue
            v = blk.var_recursive(n) if blk.has_var_recursive(n) else None
            if v is not None and getattr(v, "persistable", False):
                raise ValueError(
                    "pipeline stage %d op %r writes persistable %r; "
                    "stages must be stateless (no in-stage batch_norm "
                    "stat updates)" % (s, op.type, n))
            avail.add(n)


def pipelined_stack(input, num_stages, build_stage, num_microbatches=None,
                    name=None):
    """Run `input` through `num_stages` copies of a builder-defined stage,
    as ONE `pipeline` op (lowering: ops/parallel_ops.py).

    build_stage(x) -> y is called once per stage inside its own sub-block;
    parameters it creates become that stage's private weights (stage s
    gets an independent init draw). Stages must be homogeneous — same op
    sequence and parameter shapes — and shape-preserving (y.shape ==
    x.shape), the classic pipeline regime (e.g. a transformer encoder
    layer, a resnet block stack at fixed width).

    Execution:
      * Executor / mesh without a 'pp' axis: the stages run sequentially
        in one XLA program (identical math, zero overhead).
      * ParallelExecutor with mesh {'pp': num_stages, ...}: the GPipe
        looped pipeline of parallel/pipeline.py — stage s's weights live
        on pipeline rank s, microbatches stream over the ring via
        lax.ppermute, dp (if present) shards the microbatch dim.
        num_microbatches defaults to num_stages; more shrinks the bubble.
    Fully differentiable (grad_of takes jax.vjp of the whole schedule).

    Constraints (checked at build time): stages may not write persistable
    state (no batch_norm stat updates inside a stage), may not read
    variables from outside the stage other than their own parameters, and
    must consume/produce plain dense tensors. Random ops inside a stage
    draw per-stage (not per-microbatch) keys.
    """
    if not isinstance(input, Variable):
        raise TypeError("pipelined_stack input must be a Variable")
    if int(num_stages) < 1:
        raise ValueError("pipelined_stack needs num_stages >= 1, got %r"
                         % (num_stages,))
    helper = LayerHelper("pipeline", name=name)
    main = helper.main_program
    gb = main.global_block()

    stage_params = []      # [ [param names] per stage ]
    stage_sigs = []        # op-type sequences, for the homogeneity check
    sub0 = None
    in_name = out_name = None

    for s in range(num_stages):
        before = [p.name for p in gb.all_parameters()]
        blk = main.create_block()
        try:
            ph = blk.create_var(
                name=unique_name.generate("pipeline_stage_in"),
                dtype=input.dtype, shape=input.shape)
            out_v = build_stage(ph)
        finally:
            main.rollback()
        if not isinstance(out_v, Variable):
            raise TypeError("build_stage must return a Variable")
        if out_v.dtype != input.dtype or (
                out_v.shape is not None and input.shape is not None
                and tuple(out_v.shape) != tuple(input.shape)):
            raise ValueError(
                "pipeline stages must be shape-preserving: stage %d maps "
                "%s %s -> %s %s" % (s, input.shape, input.dtype,
                                    out_v.shape, out_v.dtype))
        seen = set(before)
        new_params = [p.name for p in gb.all_parameters()
                      if p.name not in seen]
        # self-containment: reads resolve to the placeholder, the stage's
        # own params, or values produced earlier in the stage (recursing
        # into nested control-flow sub-blocks)
        _check_stage_block(main, blk, {ph.name} | set(new_params), s)
        if not new_params:
            raise ValueError(
                "pipeline stage %d creates no parameters; per-stage "
                "weights are what pipeline parallelism distributes — a "
                "parameterless transform belongs inline, not in "
                "pipelined_stack" % s)
        stage_params.append(new_params)
        stage_sigs.append(_block_sig(main, blk))
        if s == 0:
            sub0, in_name, out_name = blk, ph.name, out_v.name
        else:
            if stage_sigs[s] != stage_sigs[0]:
                raise ValueError(
                    "pipeline stages are not homogeneous (op types/attrs "
                    "differ between stage %d and stage 0; every stage "
                    "executes stage 0's template, so divergence would be "
                    "silently ignored): %s vs %s"
                    % (s, stage_sigs[s], stage_sigs[0]))
            if len(new_params) != len(stage_params[0]):
                raise ValueError(
                    "pipeline stage %d created %d parameters but stage 0 "
                    "created %d" % (s, len(new_params),
                                    len(stage_params[0])))
            for a, b in zip(stage_params[0], new_params):
                sa, sb = gb.var(a).shape, gb.var(b).shape
                if tuple(sa or ()) != tuple(sb or ()):
                    raise ValueError(
                        "pipeline stage %d param %r shape %s != stage 0 "
                        "param %r shape %s" % (s, b, sb, a, sa))

    M = int(num_microbatches) if num_microbatches else 0
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="pipeline",
        inputs={"X": [input],
                "StageParams": [n for ps in stage_params for n in ps]},
        outputs={"Out": [out]},
        attrs={"sub_block": sub0.idx, "num_stages": int(num_stages),
               "params_per_stage": len(stage_params[0]),
               "param_names": list(stage_params[0]),
               "in_name": in_name, "out_name": out_name,
               "num_microbatches": M})
    return out


def switch_moe(input, num_experts, d_hidden, capacity_factor=1.25,
               param_attr=None, name=None):
    """Top-1 switch mixture-of-experts FFN (lowering: ops/parallel_ops.py
    -> parallel/moe.py moe_layer). input [..., D] -> (out [..., D],
    aux_loss [1]).

    Each token routes to its argmax expert (fixed capacity
    ceil(N/E * capacity_factor); overflow tokens pass through with zero
    expert output). aux_loss is the GShard load-balance term — add a small
    multiple to the training loss. Under ParallelExecutor with a mesh
    carrying an 'ep' axis the expert dim is sharded P('ep') and XLA lowers
    the dispatch/combine einsums to the all-to-all over ICI; on one chip
    the same op runs dense.
    """
    helper = LayerHelper("moe", name=name)
    dtype = input.dtype
    d = int(input.shape[-1])
    e, h = int(num_experts), int(d_hidden)
    base = ParamAttr.to_attr(param_attr)
    if base is False:
        raise ValueError("switch_moe requires parameters")

    def attr(suffix, shape, is_bias=False):
        a = ParamAttr(
            name=(base.name + "." + suffix) if base.name else None,
            initializer=base.initializer,
            learning_rate=base.learning_rate,
            regularizer=base.regularizer, trainable=base.trainable,
            gradient_clip=base.gradient_clip)
        return helper.create_parameter(attr=a, shape=shape, dtype=dtype,
                                       is_bias=is_bias)

    gate = attr("gate", [d, e])
    w1 = attr("w1", [e, d, h])
    b1 = attr("b1", [e, h], is_bias=True)
    w2 = attr("w2", [e, h, d])
    b2 = attr("b2", [e, d], is_bias=True)
    out = helper.create_variable_for_type_inference(dtype)
    aux = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="moe",
        inputs={"X": [input], "Gate": [gate], "W1": [w1], "B1": [b1],
                "W2": [w2], "B2": [b2]},
        outputs={"Out": [out], "AuxLoss": [aux]},
        attrs={"capacity_factor": float(capacity_factor)})
    return out, aux
