"""Control-flow layers: While, Switch, IfElse, StaticRNN, DynamicRNN, arrays.

Parity: python/paddle/fluid/layers/control_flow.py. The graph-building API is
preserved (sub-blocks, BlockGuards, tensor arrays, rank tables); lowering is
TPU-native — see ops/control_ops.py: While -> lax.while_loop,
Dynamic/StaticRNN -> one masked lax.scan (`rnn_scan` op), conditional blocks
-> lax.cond / row-mask select.
"""
from ..core import unique_name
from ..core.framework import Variable, default_main_program
from ..core.layer_helper import LayerHelper

__all__ = [
    "While", "Switch", "IfElse", "StaticRNN", "DynamicRNN",
    "increment", "array_write", "array_read", "array_length", "create_array",
    "less_than", "less_equal", "greater_than", "greater_equal", "equal",
    "not_equal", "is_empty", "lod_rank_table", "max_sequence_len",
    "reorder_lod_tensor_by_rank", "shrink_memory", "lod_tensor_to_array",
    "array_to_lod_tensor", "split_lod_tensor", "merge_lod_tensor",
    "Print", "ParallelDo", "get_places", "StaticRNNMemoryLink",
    "BlockGuardWithCompletion", "BlockGuard", "WhileGuard",
    "ConditionalBlock", "Select",
]


class BlockGuard(object):
    """Enter a new sub-block of `program`; pop back on exit.

    Parity: control_flow.py BlockGuard."""

    def __init__(self, program):
        self.program = program

    def __enter__(self):
        self.block = self.program.create_block()
        return self.block

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.program.rollback()
        return False


def _written_names(block):
    """Names written by `block`'s ops (including nested sub-blocks)."""
    names = set()
    blocks = [block]
    for b in block.program.blocks:
        if any(p is not None and b.parent_idx == p.idx for p in blocks):
            blocks.append(b)
    for b in blocks:
        for op in b.ops:
            names.update(n for n in op.all_output_vars() if n)
    return names


def _read_names(block):
    """Names read (in order, deduped) by `block`'s ops incl. nested blocks."""
    seen, order = set(), []
    blocks = [block]
    for b in block.program.blocks:
        if any(b.parent_idx == p.idx for p in blocks):
            blocks.append(b)
    for b in blocks:
        for op in b.ops:
            for n in op.all_input_vars():
                if n and n not in seen:
                    seen.add(n)
                    order.append(n)
    return order


# ---------------------------------------------------------------------------
# scalar helpers
# ---------------------------------------------------------------------------

def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment", **locals())
    out = x if in_place else helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="increment", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"step": float(value)},
                     infer_shape=False)
    return out


def _compare(op_type):
    def fn(x, y, cond=None, **ignored):
        helper = LayerHelper(op_type, x=x, y=y)
        if cond is None:
            cond = helper.create_variable_for_type_inference("bool")
            cond.stop_gradient = True
        helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                         outputs={"Out": [cond]})
        return cond
    fn.__name__ = op_type
    return fn


less_than = _compare("less_than")
less_equal = _compare("less_equal")
greater_than = _compare("greater_than")
greater_equal = _compare("greater_equal")
equal = _compare("equal")
not_equal = _compare("not_equal")


def is_empty(x, cond=None, **ignored):
    helper = LayerHelper("is_empty", x=x)
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool")
        cond.stop_gradient = True
    helper.append_op(type="is_empty", inputs={"X": [x]},
                     outputs={"Out": [cond]}, infer_shape=False)
    return cond


# ---------------------------------------------------------------------------
# tensor arrays / rank tables
# ---------------------------------------------------------------------------

def create_array(dtype, capacity=None):
    """Create a LoDTensorArray var. `capacity` (TPU extension) fixes the
    stacked-buffer length; default ops/control_ops.DEFAULT_ARRAY_CAPACITY."""
    helper = LayerHelper("array")
    arr = helper.block.create_var(
        name=unique_name.generate("array"), dtype=dtype)
    arr.is_tensor_array = True
    arr.capacity = capacity
    return arr


def array_write(x, i, array=None):
    helper = LayerHelper("array_write", **locals())
    if array is None:
        array = create_array(x.dtype)
    helper.append_op(type="write_to_array",
                     inputs={"X": [x], "I": [i]},
                     outputs={"Out": [array]}, infer_shape=False)
    if array.shape is None:
        array.shape = x.shape  # element shape, used by array_read infer
        array.dtype = x.dtype
    return array


def array_read(array, i):
    helper = LayerHelper("array_read", **locals())
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op(type="read_from_array",
                     inputs={"X": [array], "I": [i]},
                     outputs={"Out": [out]}, infer_shape=False)
    out.shape = array.shape
    return out


def array_length(array):
    helper = LayerHelper("array_length", **locals())
    out = helper.create_variable_for_type_inference("int32")
    out.stop_gradient = True
    out.shape = (1,)
    helper.append_op(type="lod_array_length", inputs={"X": [array]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def lod_rank_table(x, level=0):
    helper = LayerHelper("lod_rank_table", **locals())
    if x.seq_len_var is None:
        raise ValueError("lod_rank_table needs a sequence input")
    table = helper.block.create_var(
        name=unique_name.generate("lod_rank_table"), dtype="int32")
    helper.append_op(
        type="lod_rank_table",
        inputs={"XLen": [helper.block.var_recursive(x.seq_len_var)]},
        outputs={"Out": [table]}, attrs={"level": level}, infer_shape=False)
    return table


def max_sequence_len(rank_table):
    helper = LayerHelper("max_seqence_len", **locals())
    out = helper.create_variable_for_type_inference("int32")
    out.stop_gradient = True
    out.shape = (1,)
    helper.append_op(type="max_sequence_len",
                     inputs={"RankTable": [rank_table]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    helper = LayerHelper("reorder_lod_tensor_by_rank", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    inputs = {"X": [x], "RankTable": [rank_table]}
    outputs = {"Out": [out]}
    if x.seq_len_var is not None:
        out_len = helper.block.create_var(
            name=out.name + "@SEQLEN", shape=[-1], dtype="int32",
            stop_gradient=True)
        inputs["XLen"] = [helper.block.var_recursive(x.seq_len_var)]
        outputs["OutLen"] = [out_len]
        out.lod_level = x.lod_level
        out.seq_len_var = out_len.name
    helper.append_op(type="reorder_lod_tensor_by_rank", inputs=inputs,
                     outputs=outputs, infer_shape=False)
    return out


def shrink_memory(x, i, table):
    helper = LayerHelper("shrink_memory", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type="shrink_rnn_memory",
                     inputs={"X": [x], "I": [i], "RankTable": [table]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def lod_tensor_to_array(x, table=None):
    helper = LayerHelper("lod_tensor_to_array", **locals())
    arr = create_array(x.dtype)
    inputs = {"X": [x]}
    if table is not None:
        inputs["RankTable"] = [table]
        arr.rank_table_var = table.name
    helper.append_op(type="lod_tensor_to_array",
                     inputs=inputs, outputs={"Out": [arr]},
                     infer_shape=False)
    if x.shape is not None:
        arr.shape = (x.shape[0],) + tuple(x.shape[2:])
    return arr


def array_to_lod_tensor(x, table=None):
    helper = LayerHelper("array_to_lod_tensor", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out_len = helper.block.create_var(
        name=out.name + "@SEQLEN", shape=[-1], dtype="int32",
        stop_gradient=True)
    inputs = {"X": [x]}
    if table is not None:
        inputs["RankTable"] = [table]
    elif getattr(x, "rank_table_var", None):
        inputs["RankTable"] = [x.rank_table_var]
    helper.append_op(type="array_to_lod_tensor",
                     inputs=inputs,
                     outputs={"Out": [out], "OutLen": [out_len]},
                     infer_shape=False)
    # time dim is the array capacity; the written length rides the lengths
    # companion so sequence ops mask the zero tail
    out.lod_level = 1
    out.seq_len_var = out_len.name
    return out


def split_lod_tensor(input, mask, level=0):
    helper = LayerHelper("split_lod_tensor", **locals())
    out_true = helper.create_variable_for_type_inference(input.dtype)
    out_false = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="split_lod_tensor",
                     inputs={"X": [input], "Mask": [mask]},
                     outputs={"OutTrue": [out_true], "OutFalse": [out_false]},
                     attrs={"level": level})
    return out_true, out_false


def merge_lod_tensor(in_true, in_false, x, mask, level=0):
    helper = LayerHelper("merge_lod_tensor", **locals())
    out = helper.create_variable_for_type_inference(in_true.dtype)
    helper.append_op(type="merge_lod_tensor",
                     inputs={"InTrue": [in_true], "InFalse": [in_false],
                             "X": [x], "Mask": [mask]},
                     outputs={"Out": [out]}, attrs={"level": level})
    return out


# ---------------------------------------------------------------------------
# While
# ---------------------------------------------------------------------------

class While(object):
    """while cond: run block. Lowered to one lax.while_loop.

    Parity: control_flow.py `While` (while_op.cc). Vars written in the block
    that live in an enclosing block form the loop carry; tensor arrays
    carried through the loop must be written once before it (the standard
    fluid decoder idiom already does this).
    """
    BEFORE_WHILE_BLOCK = 0
    IN_WHILE_BLOCK = 1
    AFTER_WHILE_BLOCK = 2

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("while", name=name)
        self.status = While.BEFORE_WHILE_BLOCK
        if not isinstance(cond, Variable):
            raise TypeError("condition should be a Variable")
        self.cond_var = cond

    def block(self):
        return WhileGuard(self)

    def complete(self):
        program = self.helper.main_program
        while_block = program.current_block()
        parent_block = program.blocks[while_block.parent_idx]

        carry = []
        for name in sorted(_written_names(while_block)):
            if not while_block.has_var(name) and name != self.cond_var.name:
                carry.append(name)
        out_vars = [parent_block.var_recursive(n) for n in carry
                    if parent_block.has_var_recursive(n)]

        # carried vars are listed as inputs too ("X") so state analysis loads
        # persistable carries from the Scope before marking them written
        parent_block.append_op(
            type="while",
            inputs={"Condition": [self.cond_var], "X": out_vars},
            outputs={"Out": out_vars},
            attrs={"sub_block": while_block.idx,
                   "carry_names": [v.name for v in out_vars]},
            infer_shape=False)


class WhileGuard(BlockGuard):
    def __init__(self, while_op):
        super(WhileGuard, self).__init__(while_op.helper.main_program)
        self.while_op = while_op

    def __enter__(self):
        self.while_op.status = While.IN_WHILE_BLOCK
        return super(WhileGuard, self).__enter__()

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        self.while_op.status = While.AFTER_WHILE_BLOCK
        self.while_op.complete()
        return super(WhileGuard, self).__exit__(exc_type, exc_val, exc_tb)


# ---------------------------------------------------------------------------
# Switch
# ---------------------------------------------------------------------------

class ConditionalBlock(object):
    """Scalar-condition conditional block (building block of Switch).

    Parity: control_flow.py ConditionalBlock / conditional_block_op.cc."""

    def __init__(self, inputs, is_scalar_condition=True, name=None):
        self.inputs = inputs
        self.is_scalar_condition = is_scalar_condition
        self.helper = LayerHelper("conditional_block", name=name)

    def block(self):
        return ConditionalBlockGuard(self)

    def complete(self):
        program = self.helper.main_program
        inside_block = program.current_block()
        parent_block = program.blocks[inside_block.parent_idx]
        out_names = [n for n in sorted(_written_names(inside_block))
                     if not inside_block.has_var(n)
                     and parent_block.has_var_recursive(n)]
        # OutPrev: the out vars' previous values are read by the not-taken
        # branch, so they must appear as inputs for state analysis to load
        # scope-initialized (persistable) values
        parent_block.append_op(
            type="conditional_block",
            inputs={"Cond": [v.name for v in self.inputs],
                    "OutPrev": out_names},
            outputs={"Out": out_names},
            attrs={"sub_block": inside_block.idx,
                   "out_names": out_names,
                   "is_scalar_condition": self.is_scalar_condition},
            infer_shape=False)


class ConditionalBlockGuard(BlockGuard):
    def __init__(self, cond_block):
        super(ConditionalBlockGuard, self).__init__(
            cond_block.helper.main_program)
        self.cond_block = cond_block

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is None:
            self.cond_block.complete()
        return super(ConditionalBlockGuard, self).__exit__(
            exc_type, exc_val, exc_tb)


class Switch(object):
    """switch { case(cond): ... default: ... } — first matching case wins.

    Parity: control_flow.py `Switch` (used by learning-rate schedules).
    Each case lowers to a conditional_block guarded by
    cond_i AND NOT(any earlier cond).
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.inside_scope = False
        self.pre_not_taken = None  # Variable: no earlier case matched

    def case(self, condition):
        if not self.inside_scope:
            raise ValueError("case should be called inside with")
        from . import tensor, ops
        if self.pre_not_taken is None:
            eff = condition
            not_cond = ops.logical_not(x=condition)
            self.pre_not_taken = not_cond
        else:
            eff = ops.logical_and(x=self.pre_not_taken, y=condition)
            self.pre_not_taken = ops.logical_and(
                x=self.pre_not_taken, y=ops.logical_not(x=condition))
        cb = ConditionalBlock([eff], is_scalar_condition=True)
        return cb.block()

    def default(self):
        if self.pre_not_taken is None:
            raise ValueError("there should be at least one case before default")
        cb = ConditionalBlock([self.pre_not_taken], is_scalar_condition=True)
        return cb.block()

    def __enter__(self):
        self.inside_scope = True
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.inside_scope = False
        return False


# ---------------------------------------------------------------------------
# IfElse
# ---------------------------------------------------------------------------

class IfElse(object):
    """Row-wise conditional: rows of the batch where `cond` holds flow
    through the true block, the rest through the false block.

    Parity: control_flow.py `IfElse` (split_lod_tensor/merge_lod_tensor +
    conditional_block). TPU lowering computes BOTH branches on the full
    batch and selects per row with the mask — static shapes, no ragged
    sub-batches (see ops/control_ops.py).
    """
    OUT_IF_ELSE_BLOCKS = 0
    IN_IF_ELSE_TRUE_BLOCKS = 1
    IN_IF_ELSE_FALSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self.input_table = {}
        self.status = IfElse.OUT_IF_ELSE_BLOCKS
        self.conditional_true_block = ConditionalBlock(
            [cond], is_scalar_condition=False)
        self.conditional_false_block = ConditionalBlock(
            [cond], is_scalar_condition=False)
        self.output_table = [[], []]  # (true_out, false_out)

    def input(self, x):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("input must be called inside a block")
        # both branches see the full batch; mask select happens at merge
        return x

    def _block(self, status):
        ie = self

        class _Guard(BlockGuard):
            def __init__(self):
                super(_Guard, self).__init__(ie.helper.main_program)

            def __enter__(self):
                ie.status = status
                return super(_Guard, self).__enter__()

            def __exit__(self, t, v, tb):
                if t is None:
                    cb = (ie.conditional_true_block
                          if status == IfElse.IN_IF_ELSE_TRUE_BLOCKS
                          else ie.conditional_false_block)
                    cb.complete()
                ie.status = IfElse.OUT_IF_ELSE_BLOCKS
                return super(_Guard, self).__exit__(t, v, tb)

        return _Guard()

    def true_block(self):
        return self._block(IfElse.IN_IF_ELSE_TRUE_BLOCKS)

    def false_block(self):
        return self._block(IfElse.IN_IF_ELSE_FALSE_BLOCKS)

    def output(self, *outs):
        if self.status == IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("output can only be invoked in an if/else block")
        false_side = self.status == IfElse.IN_IF_ELSE_FALSE_BLOCKS
        table = self.output_table[1 if false_side else 0]
        from . import tensor
        parent_block = self.helper.main_program.blocks[
            self.helper.main_program.current_block().parent_idx]
        for o in outs:
            outside = parent_block.create_var(
                name=unique_name.generate("ifelse_out"),
                dtype=o.dtype, shape=o.shape)
            tensor.assign(o, outside)
            table.append(outside)

    def __call__(self):
        if self.status != IfElse.OUT_IF_ELSE_BLOCKS:
            raise ValueError("__call__ only at out-block status")
        if len(self.output_table[0]) != len(self.output_table[1]):
            raise ValueError("true/false blocks must produce the same number "
                             "of outputs")
        rlist = []
        for t, f in zip(*self.output_table):
            rlist.append(merge_lod_tensor(t, f, t, self.cond))
        return rlist


# ---------------------------------------------------------------------------
# rnn_scan builders (StaticRNN / DynamicRNN)
# ---------------------------------------------------------------------------

class _RNNBase(object):
    """Shared machinery: records a step sub-block + links, then emits one
    `rnn_scan` op (masked lax.scan) in the parent block."""

    BEFORE_RNN_BLOCK = 0
    IN_RNN_BLOCK = 1
    AFTER_RNN_BLOCK = 2

    def __init__(self, layer_type, name=None):
        self.helper = LayerHelper(layer_type, name=name)
        self.status = self.BEFORE_RNN_BLOCK
        self._step_inputs = []    # (outer Variable, inner placeholder)
        self._memories = []       # dict(boot, pre, update)
        self._outputs = []        # (inner Variable, outer Variable)
        self._step_block = None
        self._seq_var = None      # first sequence step input (for SeqLen)
        self._masked = True

    # -- block guard --------------------------------------------------------
    def _assert_in_rnn_block(self, method):
        if self.status != self.IN_RNN_BLOCK:
            raise ValueError("you must invoke %s inside rnn block" % method)

    def step(self):
        return _RNNGuard(self)

    block = step  # DynamicRNN spells it block()

    # -- step API -----------------------------------------------------------
    def step_input(self, x, level=0):
        self._assert_in_rnn_block("step_input")
        if not isinstance(x, Variable):
            raise TypeError("step_input takes a Variable")
        if x.shape is None or len(x.shape) < 2:
            raise ValueError("step input must be a [batch, time, ...] tensor")
        if self._seq_var is None and x.seq_len_var is not None:
            self._seq_var = x
        inner = self._step_block.create_var(
            name=unique_name.generate(self.helper.name + ".in"),
            shape=(x.shape[0],) + tuple(x.shape[2:]), dtype=x.dtype)
        self._step_inputs.append((x, inner))
        return inner

    def static_input(self, x):
        self._assert_in_rnn_block("static_input")
        # statics are closed over by name — the sub-block reads the outer var
        return x

    def memory(self, init=None, shape=None, value=0.0, init_value=0.0,
               batch_ref=None, need_reorder=False, dtype="float32",
               init_batch_dim_idx=0, ref_batch_dim_idx=1):
        self._assert_in_rnn_block("memory")
        program = self.helper.main_program
        parent_block = program.blocks[self._step_block.parent_idx]
        if init is None:
            ref = batch_ref if batch_ref is not None else (
                self._step_inputs[0][0] if self._step_inputs else None)
            if shape is None or ref is None:
                raise ValueError("memory without init needs shape and a "
                                 "step_input (or batch_ref) for the batch dim")
            boot = parent_block.create_var(
                name=unique_name.generate(self.helper.name + ".mem_boot"),
                shape=[-1] + list(shape), dtype=dtype)
            parent_block.append_op(
                type="fill_constant_batch_size_like",
                inputs={"Input": [ref]},
                outputs={"Out": [boot]},
                attrs={"value": float(value or init_value),
                       "shape": [-1] + list(shape), "dtype": dtype,
                       "input_dim_idx": 0, "output_dim_idx": 0},
                infer_shape=False)
            return self.memory(init=boot)
        pre = self._step_block.create_var(
            name=unique_name.generate(self.helper.name + ".mem"),
            shape=init.shape, dtype=init.dtype)
        self._memories.append({"boot": init, "pre": pre, "update": None})
        return pre

    def update_memory(self, ex_mem, new_mem):
        self._assert_in_rnn_block("update_memory")
        for m in self._memories:
            if m["pre"] is ex_mem or m["pre"].name == ex_mem.name:
                m["update"] = new_mem
                return
        raise ValueError("update_memory: %r is not a memory of this RNN"
                         % ex_mem.name)

    def output(self, *outputs):
        self._assert_in_rnn_block("output")
        program = self.helper.main_program
        parent_block = program.blocks[self._step_block.parent_idx]
        for o in outputs:
            outer = parent_block.create_var(
                name=unique_name.generate(self.helper.name + ".out"),
                dtype=o.dtype)
            if self._seq_var is not None:
                outer.lod_level = max(self._seq_var.lod_level, 1)
                outer.seq_len_var = self._seq_var.seq_len_var
            self._outputs.append((o, outer))

    step_output = output

    def __call__(self, *args, **kwargs):
        if self.status != self.AFTER_RNN_BLOCK:
            raise ValueError("rnn output accessible only after the rnn block")
        outs = [outer for _, outer in self._outputs]
        return outs[0] if len(outs) == 1 else outs

    # -- completion ---------------------------------------------------------
    def _complete(self):
        program = self.helper.main_program
        step_block = self._step_block
        parent_block = program.blocks[step_block.parent_idx]
        if not self._step_inputs:
            raise ValueError("RNN needs at least one step_input")
        for m in self._memories:
            if m["update"] is None:
                raise ValueError("memory %r never update_memory'd"
                                 % m["pre"].name)

        in_names = [inner.name for _, inner in self._step_inputs]
        pre_names = [m["pre"].name for m in self._memories]
        written = _written_names(step_block)
        placeholder = set(in_names) | set(pre_names)
        static_names = [
            n for n in _read_names(step_block)
            if n not in written and n not in placeholder
            and not step_block.has_var(n)
            and parent_block.has_var_recursive(n)]

        inputs = {"X": [x.name for x, _ in self._step_inputs],
                  "Boot": [m["boot"].name for m in self._memories],
                  "Static": static_names}
        if self._masked and self._seq_var is not None:
            inputs["SeqLen"] = [self._seq_var.seq_len_var]

        last_mems = []
        for m in self._memories:
            lm = parent_block.create_var(
                name=unique_name.generate(self.helper.name + ".last_mem"),
                dtype=m["boot"].dtype)
            last_mems.append(lm)
        self.final_memories = last_mems

        parent_block.append_op(
            type="rnn_scan",
            inputs=inputs,
            outputs={"Out": [outer for _, outer in self._outputs],
                     "LastMem": last_mems},
            attrs={"sub_block": step_block.idx,
                   "in_names": in_names,
                   "static_names": static_names,
                   "pre_names": pre_names,
                   "update_names": [m["update"].name for m in self._memories],
                   "out_names": [o.name for o, _ in self._outputs],
                   "max_len": None})


class _RNNGuard(BlockGuard):
    def __init__(self, rnn):
        super(_RNNGuard, self).__init__(rnn.helper.main_program)
        self.rnn = rnn

    def __enter__(self):
        self.rnn.status = self.rnn.IN_RNN_BLOCK
        blk = super(_RNNGuard, self).__enter__()
        self.rnn._step_block = blk
        return blk

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        self.rnn.status = self.rnn.AFTER_RNN_BLOCK
        self.rnn._complete()
        return super(_RNNGuard, self).__exit__(exc_type, exc_val, exc_tb)


class StaticRNN(_RNNBase):
    """Fixed-length RNN over [batch, time, ...] inputs (no length masking).

    Parity: control_flow.py StaticRNN / recurrent_op.cc. Lowered to one
    lax.scan; BPTT comes from jax.vjp of the scan."""

    def __init__(self, name=None):
        super(StaticRNN, self).__init__("static_rnn", name)
        self._masked = False


class DynamicRNN(_RNNBase):
    """Variable-length RNN over padded sequences: memories freeze and
    outputs zero past each row's true length.

    Parity: control_flow.py DynamicRNN (which expands to lod_rank_table +
    lod_tensor_to_array + While + shrink_memory). Here it is ONE masked
    lax.scan — same math, static shapes, MXU-batched gate matmuls."""

    def __init__(self, name=None):
        super(DynamicRNN, self).__init__("dynamic_rnn", name)

    def step_input(self, x, level=0):
        if x.seq_len_var is None:
            raise ValueError(
                "DynamicRNN.step_input needs a sequence (lod_level>0) input")
        return super(DynamicRNN, self).step_input(x, level)


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Parity: fluid.layers.Print (reference control_flow.py:150,
    print_op.cc). Wraps the tensor so each execution prints `message` and
    the value; lowered to jax.debug.print, which works inside jit and on
    device. The op is identity, so gradients pass through unchanged
    (print_phase is accepted; values print whenever the op executes,
    including its recompute inside the backward's vjp). Returns the
    identity output so the print stays live in the graph."""
    helper = LayerHelper("print", name=None)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="print", inputs={"In": [input]}, outputs={"Out": [out]},
        attrs={"first_n": int(first_n), "message": message or "",
               "summarize": int(summarize),
               "print_tensor_name": bool(print_tensor_name),
               "print_tensor_type": bool(print_tensor_type),
               "print_tensor_shape": bool(print_tensor_shape),
               "print_phase": str(print_phase),
               "var_name": input.name})
    if input.shape is not None:
        out.shape = tuple(input.shape)
    return out


def get_places(device_count=None, device_type=None):
    """Parity: fluid.layers.get_places — the reference returned a places
    variable for ParallelDo. Device placement is mesh-declarative here, so
    this returns the device list for inspection (filtered to device_type
    when given, e.g. 'CPU')."""
    import jax
    devices = jax.devices(device_type.lower()) if device_type \
        else jax.devices()
    if device_count is not None:
        devices = devices[:device_count]
    return devices


class ParallelDo(object):
    """Parity shim: reference control_flow.py ParallelDo replicated a
    sub-block over GPUs with gradient all-reduce (parallel_do_op.cc). The
    TPU-native equivalent is GSPMD data parallelism (ParallelExecutor), so
    this shim runs the body INLINE on the full batch — numerically the
    behavior ParallelDo produced, with the device distribution delegated to
    the mesh. Kept so reference scripts run unchanged."""

    def __init__(self, places, use_nccl=False, name=None):
        self._outputs = []

    def do(self):
        import contextlib

        @contextlib.contextmanager
        def guard():
            yield
        return guard()

    def read_input(self, var):
        return var

    def write_output(self, var):
        self._outputs.append(var)

    def __call__(self):
        if not self._outputs:
            raise ValueError("ParallelDo: no outputs written; call "
                             "write_output inside the do() block")
        return self._outputs[0] if len(self._outputs) == 1 \
            else list(self._outputs)


class StaticRNNMemoryLink(object):
    """Parity: reference control_flow.py StaticRNNMemoryLink — the
    (init, pre_mem, mem) record linking a memory across steps. The scan
    lowering tracks this inside _RNNBase; the class is kept for scripts
    that introspect it."""

    def __init__(self, init, pre_mem, mem=None):
        self.init = init
        self.pre_mem = pre_mem
        self.mem = mem


class BlockGuardWithCompletion(_RNNGuard):
    """Parity: reference control_flow.py BlockGuardWithCompletion — the
    with-block helper that completes the RNN on exit. Functionally the same
    guard rnn.block()/step() return (_RNNGuard: sets IN_RNN_BLOCK, opens
    the step sub-block, emits the rnn_scan op on exit), kept under the
    reference name for scripts that construct it directly."""


class Select(object):
    """Parity placeholder: fluid.Select (the CSP-style channel select from
    fluid.concurrency). The concurrency surface is an explicit scope cut —
    see SURVEY.md §2: its blocking-channel semantics contradict whole-
    program XLA execution; the TPU-native equivalents are the async reader
    layers (double_buffer) and collective-based parallelism."""

    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "fluid.concurrency channels/Select are not rebuilt in "
            "paddle_tpu (explicit scope cut, SURVEY.md §2); use the reader "
            "layers (double_buffer) for async input or ParallelExecutor "
            "collectives for parallelism")
