"""Python layer entries for the long-tail operator library (ops/tail_ops.py).

The reference registered these ops in C++ (paddle/fluid/operators/
{prelu,pad,crop,roi_pool,sequence_slice,sequence_concat,pool_with_index,
unpool,spp,norm,l1_norm,squared_l2_norm,squared_l2_distance,
modified_huber_loss,conv_shift,bilinear_tensor_product,precision_recall,
positive_negative_pair}_op.cc) without exposing era Python wrappers; these
thin layers make the ops reachable from the Program path and are NOT added
to the frozen reference-__all__ parity surface.
"""
from ..core.layer_helper import LayerHelper
from ..core.initializer import ConstantInitializer
from .sequence import _seq_len

__all__ = [
    "prelu", "pad", "crop", "roi_pool", "sequence_slice", "sequence_concat",
    "max_pool2d_with_index", "unpool", "spp", "norm", "l1_norm",
    "squared_l2_norm", "squared_l2_distance", "modified_huber_loss",
    "conv_shift", "bilinear_tensor_product", "precision_recall",
    "positive_negative_pair",
]


def prelu(x, param_attr=None, name=None):
    """Scalar-alpha PReLU (prelu_op.cc: Alpha has exactly one element)."""
    helper = LayerHelper("prelu", **locals())
    alpha = helper.create_parameter(
        attr=helper.param_attr, shape=[1], dtype=x.dtype,
        default_initializer=ConstantInitializer(0.25))
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="prelu", inputs={"X": [x], "Alpha": [alpha]},
                     outputs={"Out": [out]})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="pad", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"paddings": [int(p) for p in paddings],
                            "pad_value": float(pad_value)})
    return out


def crop(x, shape, offsets=None, name=None):
    helper = LayerHelper("crop", **locals())
    if offsets is None:
        offsets = [0] * len(shape)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="crop", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"shape": [int(s) for s in shape],
                            "offsets": [int(o) for o in offsets]})
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0):
    helper = LayerHelper("roi_pool", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    argmax = helper.create_variable_for_type_inference("int64")
    argmax.stop_gradient = True
    helper.append_op(
        type="roi_pool", inputs={"X": [input], "ROIs": [rois]},
        outputs={"Out": [out], "Argmax": [argmax]},
        attrs={"pooled_height": int(pooled_height),
               "pooled_width": int(pooled_width),
               "spatial_scale": float(spatial_scale)})
    return out


def sequence_slice(input, offset, length, name=None):
    helper = LayerHelper("sequence_slice", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out_len = helper.block.create_var(
        name=out.name + "@SEQLEN", shape=[-1], dtype="int32",
        stop_gradient=True)
    helper.append_op(
        type="sequence_slice",
        inputs={"X": [input], "Offset": [offset], "Length": [length]},
        outputs={"Out": [out], "OutLen": [out_len]})
    out.lod_level = max(input.lod_level, 1)
    out.seq_len_var = out_len.name
    return out


def sequence_concat(input, axis=0, name=None):
    """Concatenate a list of sequences (axis=0: along time per sequence)."""
    helper = LayerHelper("sequence_concat", **locals())
    out = helper.create_variable_for_type_inference(input[0].dtype)
    out_len = helper.block.create_var(
        name=out.name + "@SEQLEN", shape=[-1], dtype="int32",
        stop_gradient=True)
    helper.append_op(
        type="sequence_concat",
        inputs={"X": list(input),
                "XLen": [_seq_len(helper, x) for x in input]},
        outputs={"Out": [out], "OutLen": [out_len]},
        attrs={"axis": int(axis)})
    out.lod_level = max(input[0].lod_level, 1)
    out.seq_len_var = out_len.name
    return out


def max_pool2d_with_index(input, pool_size, pool_stride=1, pool_padding=0,
                          global_pooling=False, name=None):
    from ..core.utils import pair as _pair
    helper = LayerHelper("max_pool2d_with_index", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    mask = helper.create_variable_for_type_inference("int32")
    mask.stop_gradient = True
    helper.append_op(
        type="max_pool2d_with_index", inputs={"X": [input]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={"ksize": list(_pair(pool_size)),
               "strides": list(_pair(pool_stride)),
               "paddings": list(_pair(pool_padding)),
               "global_pooling": bool(global_pooling)})
    return out, mask


def unpool(input, indices, pool_size, pool_stride=1, pool_padding=0,
           name=None):
    from ..core.utils import pair as _pair
    helper = LayerHelper("unpool", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="unpool", inputs={"X": [input], "Indices": [indices]},
        outputs={"Out": [out]},
        attrs={"ksize": list(_pair(pool_size)),
               "strides": list(_pair(pool_stride)),
               "paddings": list(_pair(pool_padding)),
               "unpooling_type": "max"})
    return out


def spp(input, pyramid_height, pool_type="max", name=None):
    helper = LayerHelper("spp", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="spp", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"pyramid_height": int(pyramid_height),
                            "pooling_type": pool_type})
    return out


def norm(input, epsilon=1e-10, param_attr=None, name=None):
    """Cross-channel L2 normalization with per-channel scale (norm_op.cc,
    the SSD L2Norm layer)."""
    helper = LayerHelper("norm", **locals())
    scale = helper.create_parameter(
        attr=helper.param_attr, shape=[input.shape[1], 1], dtype=input.dtype,
        default_initializer=ConstantInitializer(1.0))
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="norm", inputs={"X": [input], "Scale": [scale]},
                     outputs={"Out": [out]},
                     attrs={"epsilon": float(epsilon)})
    return out


def _unary_scalar(op_type, x, name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type=op_type, inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def l1_norm(x, name=None):
    return _unary_scalar("l1_norm", x, name)


def squared_l2_norm(x, name=None):
    return _unary_scalar("squared_l2_norm", x, name)


def squared_l2_distance(x, y, name=None):
    helper = LayerHelper("squared_l2_distance", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    sub = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="squared_l2_distance",
                     inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out], "sub_result": [sub]})
    return out


def modified_huber_loss(x, y, name=None):
    helper = LayerHelper("modified_huber_loss", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    inter = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="modified_huber_loss",
                     inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out], "IntermediateVal": [inter]})
    return out


def conv_shift(x, y, name=None):
    helper = LayerHelper("conv_shift", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="conv_shift", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def bilinear_tensor_product(x, y, size, param_attr=None, bias_attr=None,
                            act=None, name=None):
    helper = LayerHelper("bilinear_tensor_product", **locals())
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[size, x.shape[-1], y.shape[-1]],
        dtype=x.dtype)
    inputs = {"X": [x], "Y": [y], "Weight": [w]}
    if bias_attr is not False:
        bias = helper.create_parameter(
            attr=helper.bias_attr, shape=[1, size], dtype=x.dtype,
            is_bias=True)
        inputs["Bias"] = [bias]
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="bilinear_tensor_product", inputs=inputs,
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def precision_recall(indices, labels, class_number, weights=None,
                     states_info=None, name=None):
    """Multiclass precision/recall/F1 (precision_recall_op.cc). Returns
    (batch_metrics [6], accum_metrics [6], accum_states [C, 4])."""
    helper = LayerHelper("precision_recall", **locals())
    batch = helper.create_variable_for_type_inference("float32")
    accum = helper.create_variable_for_type_inference("float32")
    states = helper.create_variable_for_type_inference("float32")
    inputs = {"Indices": [indices], "Labels": [labels]}
    if weights is not None:
        inputs["Weights"] = [weights]
    if states_info is not None:
        inputs["StatesInfo"] = [states_info]
    helper.append_op(
        type="precision_recall", inputs=inputs,
        outputs={"BatchMetrics": [batch], "AccumMetrics": [accum],
                 "AccumStatesInfo": [states]},
        attrs={"class_number": int(class_number)})
    return batch, accum, states


def positive_negative_pair(score, label, query_id, weight=None,
                           accum=None, column=-1, name=None):
    """LTR correctly/incorrectly-ordered pair counts
    (positive_negative_pair_op.cc). Returns (pos, neg, neu) [1] each."""
    helper = LayerHelper("positive_negative_pair", **locals())
    pos = helper.create_variable_for_type_inference("float32")
    neg = helper.create_variable_for_type_inference("float32")
    neu = helper.create_variable_for_type_inference("float32")
    inputs = {"Score": [score], "Label": [label], "QueryID": [query_id]}
    if weight is not None:
        inputs["Weight"] = [weight]
    if accum is not None:
        inputs["AccumulatePositivePair"] = [accum[0]]
        inputs["AccumulateNegativePair"] = [accum[1]]
        inputs["AccumulateNeutralPair"] = [accum[2]]
    helper.append_op(
        type="positive_negative_pair", inputs=inputs,
        outputs={"PositivePair": [pos], "NegativePair": [neg],
                 "NeutralPair": [neu]},
        attrs={"column": int(column)})
    return pos, neg, neu
