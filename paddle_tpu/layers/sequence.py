"""Sequence layers (LoD-aware).

Parity: the sequence_* / dynamic_* functions of python/paddle/fluid/layers/nn.py.
"""
from ..core.framework import Variable
from ..core.layer_helper import LayerHelper
from ..core.param_attr import ParamAttr

__all__ = [
    "sequence_pool", "sequence_first_step", "sequence_last_step",
    "sequence_softmax", "sequence_conv", "sequence_expand", "sequence_reshape",
    "dynamic_lstm", "dynamic_lstmp", "dynamic_gru", "gru_unit", "lstm_unit",
    "lod_reset", "row_conv", "beam_search", "beam_search_decode",
    "sequence_cache_write",
]


def _seq_len(helper, x):
    if x.seq_len_var is None:
        raise ValueError(
            "%r is not a sequence (lod_level=0); sequence layers need an "
            "input produced from a lod_level>0 data layer" % x.name)
    return helper.block.var_recursive(x.seq_len_var)


def sequence_pool(input, pool_type, is_test=False):
    helper = LayerHelper("sequence_pool", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="sequence_pool",
        inputs={"X": [input], "XLen": [_seq_len(helper, input)]},
        outputs={"Out": [out]},
        attrs={"pooltype": pool_type.upper()})
    out.lod_level = 0
    out.seq_len_var = None
    return out


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_softmax(input, use_cudnn=False, name=None):
    helper = LayerHelper("sequence_softmax", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="sequence_softmax",
        inputs={"X": [input], "XLen": [_seq_len(helper, input)]},
        outputs={"Out": [out]})
    return out


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None):
    helper = LayerHelper("sequence_conv", **locals())
    dtype = helper.input_dtype()
    filter_shape = [filter_size * input.shape[-1], num_filters]
    filter_param = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="sequence_conv",
        inputs={"X": [input], "Filter": [filter_param],
                "XLen": [_seq_len(helper, input)]},
        outputs={"Out": [pre_bias]},
        attrs={"contextStride": filter_stride,
               "contextStart": -int(filter_size // 2),
               "contextLength": filter_size})
    pre_act = helper.append_bias_op(pre_bias, dim_start=2)
    return helper.append_activation(pre_act)


def sequence_expand(x, y, name=None):
    helper = LayerHelper("sequence_expand", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="sequence_expand",
        inputs={"X": [x], "Y": [y], "YLen": [_seq_len(helper, y)]},
        outputs={"Out": [out]})
    out.lod_level = max(y.lod_level, 1)
    out.seq_len_var = y.seq_len_var
    return out


def sequence_reshape(input, new_dim):
    """Parity: fluid.layers.sequence_reshape (sequence_reshape_op.cc) —
    repacks each sequence's row data to width new_dim; a length-L sequence
    of dim D becomes length L*D/new_dim. The registered lowering reshapes
    the padded data (valid data is a contiguous row prefix, so it stays
    contiguous) and emits the integer-rescaled OutLen companion."""
    helper = LayerHelper("sequence_reshape", **locals())
    if helper.block.idx != 0:
        # inside a While/RNN sub-block the lowering's per-sequence
        # divisibility assertion cannot escape the lax trace
        # (LowerCtx.add_error skips under _loop_iters) — the reference op
        # would hard-error on a non-divisible tail, here it would be
        # silently truncated. Surface that at build time.
        import warnings
        warnings.warn(
            "sequence_reshape inside a control-flow sub-block: the "
            "per-sequence len*dim % new_dim divisibility check is not "
            "enforceable in-graph there; a non-divisible sequence tail "
            "would be silently dropped. Verify shapes statically.",
            stacklevel=2)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_len = helper.block.create_var(
        name=out.name + "@SEQLEN", shape=[-1], dtype="int32",
        stop_gradient=True)
    helper.append_op(
        type="sequence_reshape",
        inputs={"X": [input], "XLen": [_seq_len(helper, input)]},
        outputs={"Out": [out], "OutLen": [out_len]},
        attrs={"new_dim": new_dim})
    out.lod_level = 1
    out.seq_len_var = out_len.name
    return out


def lod_reset(x, y=None, target_lod=None):
    """Re-segment x's flat data stream (reference lod_reset_op.cc: new LoD
    from Y's own LoD, Y.data offsets, or attr target_lod [0, n1, n2...];
    plain per-sequence lengths are also accepted for target_lod — a list
    whose first element is 0 is ALWAYS read as offsets, per the reference,
    so an empty-first-sequence lengths list must be given as offsets)."""
    if y is None and not target_lod:
        raise ValueError(
            "lod_reset: either y or a non-empty target_lod must be "
            "provided (reference lod_reset_op enforces the same)")
    helper = LayerHelper("lod_reset", **locals())
    if helper.block.idx != 0:
        # inside a While/RNN sub-block the lowering's length-sum assertion
        # cannot escape the lax trace (LowerCtx.add_error skips under
        # _loop_iters) — a mismatched target would silently clip or drop
        # rows. Surface that at build time, like sequence_reshape above.
        import warnings
        warnings.warn(
            "lod_reset inside a control-flow sub-block: the target-"
            "segmentation length-sum check is not enforceable in-graph "
            "there; a mismatched target_lod would silently clip or drop "
            "rows. Verify lengths statically.", stacklevel=2)
    out = helper.create_variable_for_type_inference(x.dtype)
    out_len = helper.block.create_var(
        name=out.name + "@SEQLEN", shape=[-1], dtype="int32",
        stop_gradient=True)
    inputs = {"X": [x]}
    attrs = {}
    if getattr(x, "lod_level", 0):
        inputs["XLen"] = [_seq_len(helper, x)]
    if y is not None:
        if getattr(y, "lod_level", 0):
            inputs["Y"] = [y]
            inputs["YLen"] = [_seq_len(helper, y)]
        else:
            inputs["YData"] = [y]
    elif target_lod is not None:
        tl = [int(v) for v in target_lod]
        attrs["target_lens"] = (
            [b - a for a, b in zip(tl, tl[1:])]
            if tl and tl[0] == 0 and len(tl) > 1 else tl)
    helper.append_op(type="lod_reset", inputs=inputs,
                     outputs={"Out": [out], "OutLen": [out_len]},
                     attrs=attrs)
    out.lod_level = 1
    out.seq_len_var = out_len.name
    return out


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """Parity: fluid.layers.dynamic_lstm — input must be [.., 4*hidden]
    (pre-projected by an fc), size = 4*hidden."""
    helper = LayerHelper("dynamic_lstm", **locals())
    hidden = size // 4
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[hidden, 4 * hidden], dtype=dtype)
    bias_size = [1, 7 * hidden if use_peepholes else 4 * hidden]
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=bias_size, dtype=dtype, is_bias=True)
    hidden_out = helper.create_variable_for_type_inference(dtype)
    cell_out = helper.create_variable_for_type_inference(dtype)
    batch_gate = helper.create_variable_for_type_inference(dtype)
    batch_cell_pre_act = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias],
              "XLen": [_seq_len(helper, input)]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    helper.append_op(
        type="lstm", inputs=inputs,
        outputs={"Hidden": [hidden_out], "Cell": [cell_out],
                 "BatchGate": [batch_gate],
                 "BatchCellPreAct": [batch_cell_pre_act]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation})
    return hidden_out, cell_out


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None, h_0=None, c_0=None):
    """Parity: fluid.layers.dynamic_lstmp (reference lstmp_op.cc) — LSTM
    with recurrent projection: the projected state feeds back into the
    gates, so the recurrent Weight is [proj_size, 4*hidden]. param_attr
    may be a 2-list [weight_attr, proj_weight_attr]."""
    helper = LayerHelper("dynamic_lstmp", **locals())
    hidden = size // 4
    w_attr, proj_attr = helper.multiple_param_attr(2)
    weight = helper.create_parameter(
        attr=w_attr, shape=[proj_size, 4 * hidden], dtype=dtype)
    proj_weight = helper.create_parameter(
        attr=proj_attr, shape=[hidden, proj_size], dtype=dtype)
    bias_size = [1, 7 * hidden if use_peepholes else 4 * hidden]
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=bias_size, dtype=dtype, is_bias=True)
    projection = helper.create_variable_for_type_inference(dtype)
    cell_out = helper.create_variable_for_type_inference(dtype)
    batch_gate = helper.create_variable_for_type_inference(dtype)
    batch_cell_pre_act = helper.create_variable_for_type_inference(dtype)
    batch_hidden = helper.create_variable_for_type_inference(dtype)
    ordered_p0 = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight],
              "ProjWeight": [proj_weight], "Bias": [bias],
              "XLen": [_seq_len(helper, input)]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    helper.append_op(
        type="lstmp",
        inputs=inputs,
        outputs={"Projection": [projection], "Cell": [cell_out],
                 "BatchGate": [batch_gate],
                 "BatchCellPreAct": [batch_cell_pre_act],
                 "BatchHidden": [batch_hidden], "OrderedP0": [ordered_p0]},
        attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "cell_activation": cell_activation,
               "candidate_activation": candidate_activation,
               "proj_activation": proj_activation})
    return projection, cell_out


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None):
    """Parity: fluid.layers.dynamic_gru — input [.., 3*size]."""
    helper = LayerHelper("dynamic_gru", **locals())
    dtype = helper.input_dtype()
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[size, 3 * size], dtype=dtype)
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=[1, 3 * size], dtype=dtype, is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    batch_gate = helper.create_variable_for_type_inference(dtype)
    batch_reset = helper.create_variable_for_type_inference(dtype)
    batch_hidden = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias],
              "XLen": [_seq_len(helper, input)]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    helper.append_op(
        type="gru", inputs=inputs,
        outputs={"Hidden": [hidden], "BatchGate": [batch_gate],
                 "BatchResetHiddenPrev": [batch_reset],
                 "BatchHidden": [batch_hidden]},
        attrs={"is_reverse": is_reverse,
               "gate_activation": gate_activation,
               "activation": candidate_activation})
    return hidden


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid"):
    """Parity: fluid.layers.gru_unit (one step; used in DynamicRNN decoders)."""
    helper = LayerHelper("gru_unit", **locals())
    dtype = helper.input_dtype()
    size = size // 3
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[size, 3 * size], dtype=dtype)
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=[1, 3 * size], dtype=dtype, is_bias=True)
    gate = helper.create_variable_for_type_inference(dtype)
    reset_hidden_pre = helper.create_variable_for_type_inference(dtype)
    updated_hidden = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="gru_unit",
        inputs={"Input": [input], "HiddenPrev": [hidden],
                "Weight": [weight], "Bias": [bias]},
        outputs={"Hidden": [updated_hidden], "Gate": [gate],
                 "ResetHiddenPrev": [reset_hidden_pre]},
        attrs={"activation": activation, "gate_activation": gate_activation})
    return updated_hidden, reset_hidden_pre, gate


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """Parity: fluid.layers.lstm_unit — fc(x_t ++ h_prev) then lstm_unit op."""
    from . import nn, tensor
    size = cell_t_prev.shape[-1]
    concat_out = tensor.concat(input=[x_t, hidden_t_prev], axis=-1)
    fc_out = nn.fc(input=concat_out, size=4 * size, param_attr=param_attr,
                   bias_attr=bias_attr)
    helper = LayerHelper("lstm_unit", **locals())
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op(
        type="lstm_unit",
        inputs={"X": [fc_out], "C_prev": [cell_t_prev]},
        outputs={"C": [c], "H": [h]},
        attrs={"forget_bias": forget_bias})
    return h, c


def sequence_cache_write(cache, x, pos, name=None):
    """Write each row of `x` [B, ...] into `cache` [B, T, ...] at that
    row's position `pos` [B] (TPU-native addition — the KV-cache write
    of a decode step).  Returns the updated cache; make `cache` (and
    `pos`) persistable slot state and assign the result back so
    serving.DecodeEngine keeps the cache device-resident and donated
    across iterations (ARCHITECTURE §27)."""
    helper = LayerHelper("sequence_cache_write", **locals())
    out = helper.create_variable_for_type_inference(cache.dtype)
    helper.append_op(
        type="sequence_cache_write",
        inputs={"Cache": [cache], "X": [x], "Pos": [pos]},
        outputs={"Out": [out]})
    if cache.shape is not None:
        out.shape = tuple(cache.shape)
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv", **locals())
    dtype = helper.input_dtype()
    filter_shape = [future_context_size + 1, input.shape[-1]]
    filter_param = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="row_conv",
        inputs={"X": [input], "Filter": [filter_param],
                "XLen": [_seq_len(helper, input)]},
        outputs={"Out": [out]})
    return helper.append_activation(out)


def beam_search(pre_ids, ids, scores, beam_size, end_id, level=0,
                pre_scores=None, return_parent_idx=False, name=None):
    """One beam-search expansion step, dense [batch, beam] layout.

    Parity: python/paddle/fluid/layers/nn.py beam_search /
    operators/beam_search_op.cc. The reference tracks beams in 2-level-LoD
    candidate lists; on TPU each batch row always holds exactly `beam_size`
    beams so the decode loop stays one lax.while_loop of static shapes.

    Dense contract: `scores` is [batch, beam, vocab] next-token log-probs,
    `pre_ids`/`pre_scores` are [batch, beam]. Returns (selected_ids,
    selected_scores) and, if return_parent_idx, the [batch, beam] parent
    beam index needed by beam_search_decode. `ids` (the reference's topk
    candidate path) is accepted and ignored — the op does its own top-k
    over beam*vocab.

    IMPORTANT (step 0): when all beams of a row start identical (the usual
    [start_token]*beam init), initialize pre_scores to [0, -1e9, -1e9, ...]
    per row, NOT all zeros — otherwise the top-k over beam*vocab selects the
    same best token once per duplicate beam and the search degenerates to
    beam_size copies of greedy decoding.
    """
    helper = LayerHelper("beam_search", **locals())
    if pre_scores is None:
        raise ValueError(
            "TPU beam_search needs pre_scores (cumulative log-probs); pass "
            "the previous step's selected_scores")
    selected_ids = helper.create_variable_for_type_inference(pre_ids.dtype)
    selected_scores = helper.create_variable_for_type_inference(scores.dtype)
    parent_idx = helper.create_variable_for_type_inference("int32")
    for v, sh in ((selected_ids, pre_ids.shape),
                  (selected_scores, pre_ids.shape),
                  (parent_idx, pre_ids.shape)):
        v.shape = sh
    helper.append_op(
        type="beam_search",
        inputs={"pre_ids": [pre_ids], "pre_scores": [pre_scores],
                "scores": [scores]},
        outputs={"selected_ids": [selected_ids],
                 "selected_scores": [selected_scores],
                 "parent_idx": [parent_idx]},
        attrs={"beam_size": int(beam_size), "end_id": int(end_id),
               "level": level},
        infer_shape=False)
    if return_parent_idx:
        return selected_ids, selected_scores, parent_idx
    return selected_ids, selected_scores


def beam_search_decode(ids, scores, parent_idx=None, beam_size=None,
                       end_id=0, name=None):
    """Backtrack per-step beam arrays into final sentences.

    Parity: python/paddle/fluid/layers/nn.py beam_search_decode /
    operators/beam_search_decode_op.cc. `ids`/`scores` are the TensorArrays
    written each step; `parent_idx` the array of parent beam indices from
    beam_search(return_parent_idx=True). Returns (sentence_ids [B, beam, T]
    end_id-padded, sentence_scores [B, beam]).
    """
    helper = LayerHelper("beam_search_decode", **locals())
    if parent_idx is None:
        raise ValueError("TPU beam_search_decode needs the parent_idx array "
                         "(beam_search(..., return_parent_idx=True))")
    sentence_ids = helper.create_variable_for_type_inference(ids.dtype)
    sentence_scores = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="beam_search_decode",
        inputs={"Ids": [ids], "ParentIdx": [parent_idx], "Scores": [scores]},
        outputs={"SentenceIds": [sentence_ids],
                 "SentenceScores": [sentence_scores]},
        attrs={"end_id": int(end_id)},
        infer_shape=False)
    return sentence_ids, sentence_scores
