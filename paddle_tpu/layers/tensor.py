"""Tensor creation/manipulation layers.

Parity: python/paddle/fluid/layers/tensor.py.
"""
import numpy as np

from ..core.framework import Variable, default_main_program
from ..core.layer_helper import LayerHelper
from ..core.initializer import ConstantInitializer

__all__ = [
    "create_tensor", "create_parameter", "create_global_var", "cast",
    "concat", "sums", "assign", "fill_constant",
    "fill_constant_batch_size_like", "ones", "zeros", "argmax",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", **locals())
    return helper.create_variable(name=helper.name, dtype=dtype,
                                  persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..core.param_attr import ParamAttr
    helper = LayerHelper("create_parameter", **locals())
    attr = ParamAttr.to_attr(attr)
    if attr.name is None:
        attr.name = name
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper("global_var", **locals())
    var = helper.create_global_variable(
        dtype=dtype, shape=shape, persistable=persistable, name=name)
    helper.set_variable_initializer(
        var, initializer=ConstantInitializer(value=float(value)))
    return var


def cast(x, dtype):
    helper = LayerHelper("cast", **locals())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="cast", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"in_dtype": x.dtype, "out_dtype": out.dtype})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", **locals())
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op(type="concat", inputs={"X": input},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def sums(input, out=None):
    helper = LayerHelper("sum", **locals())
    if out is None:
        out = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op(type="sum", inputs={"X": input}, outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign", **locals())
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op(type="assign", inputs={"X": [input]},
                         outputs={"Out": [output]})
    elif isinstance(input, np.ndarray):
        if output is None:
            output = helper.create_variable_for_type_inference(
                str(input.dtype))
        helper.append_op(
            type="assign_value", outputs={"Out": [output]},
            attrs={"shape": list(input.shape), "dtype": str(input.dtype),
                   "values": input.reshape(-1).tolist()},
            infer_shape=False)
        output.shape = tuple(input.shape)
    else:
        raise TypeError("assign expects Variable or ndarray")
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant", **locals())
    if out is None:
        out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="fill_constant", outputs={"Out": [out]},
        attrs={"shape": [int(s) for s in shape], "dtype": dtype,
               "value": float(value)},
        infer_shape=False)
    out.shape = tuple(int(s) for s in shape)
    out.dtype = out.dtype or dtype
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like", **locals())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="fill_constant_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"shape": [int(s) for s in shape], "dtype": dtype,
               "value": float(value), "input_dim_idx": input_dim_idx,
               "output_dim_idx": output_dim_idx})
    out.stop_gradient = True
    return out


def ones(shape, dtype, force_cpu=False):
    return fill_constant(value=1.0, shape=shape, dtype=dtype)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(value=0.0, shape=shape, dtype=dtype)


def argmax(x, axis=0):
    helper = LayerHelper("arg_max", **locals())
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="arg_max", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out
