"""Python operator overloading on Variable.

Parity: python/paddle/fluid/layers/math_op_patch.py (monkey_patch_variable).
"""
from ..core.framework import Variable
from ..core.layer_helper import LayerHelper
from ..core import unique_name


def _create_scalar_op(block, value, dtype, shape):
    helper = LayerHelper("scalar")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="fill_constant", outputs={"Out": [out]},
        attrs={"shape": list(shape or [1]), "dtype": dtype,
               "value": float(value)}, infer_shape=False)
    out.shape = tuple(shape or (1,))
    out.stop_gradient = True
    return out


def _elementwise_method(op_type, reverse=False, scalar_as_scale=None):
    def method(self, other):
        helper = LayerHelper(op_type)
        if isinstance(other, (int, float)):
            # scalar fast paths: x+c, x*c -> scale op (fused by XLA anyway)
            if scalar_as_scale and not reverse:
                out = helper.create_variable_for_type_inference(self.dtype)
                attrs = dict(scalar_as_scale(other))
                helper.append_op(type="scale", inputs={"X": [self]},
                                 outputs={"Out": [out]}, attrs=attrs)
                return out
            other = _create_scalar_op(self.block, other, self.dtype,
                                      None)
        x, y = (other, self) if reverse else (self, other)
        out = helper.create_variable_for_type_inference(self.dtype)
        helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                         outputs={"Out": [out]}, attrs={"axis": -1})
        return out
    return method


def monkey_patch_variable():
    Variable.__add__ = _elementwise_method(
        "elementwise_add", scalar_as_scale=lambda c: {"scale": 1.0, "bias": c})
    Variable.__radd__ = Variable.__add__
    Variable.__sub__ = _elementwise_method(
        "elementwise_sub", scalar_as_scale=lambda c: {"scale": 1.0, "bias": -c})
    Variable.__rsub__ = _elementwise_method("elementwise_sub", reverse=True)
    Variable.__mul__ = _elementwise_method(
        "elementwise_mul", scalar_as_scale=lambda c: {"scale": c})
    Variable.__rmul__ = Variable.__mul__
    Variable.__div__ = _elementwise_method("elementwise_div")
    Variable.__truediv__ = Variable.__div__
    Variable.__rdiv__ = _elementwise_method("elementwise_div", reverse=True)
    Variable.__rtruediv__ = Variable.__rdiv__
    Variable.__pow__ = _elementwise_method("elementwise_pow")
    Variable.__rpow__ = _elementwise_method("elementwise_pow", reverse=True)
    Variable.__neg__ = lambda self: self * (-1.0)
    Variable.__lt__ = _compare_method("less_than")
    Variable.__le__ = _compare_method("less_equal")
    Variable.__gt__ = _compare_method("greater_than")
    Variable.__ge__ = _compare_method("greater_equal")


def _compare_method(op_type):
    def method(self, other):
        helper = LayerHelper(op_type)
        if isinstance(other, (int, float)):
            other = _create_scalar_op(self.block, other, self.dtype, None)
        out = helper.create_variable_for_type_inference("bool")
        helper.append_op(type=op_type, inputs={"X": [self], "Y": [other]},
                         outputs={"Out": [out]})
        return out
    return method
