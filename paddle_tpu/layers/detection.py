"""Detection layers (SSD family).

Parity: python/paddle/fluid/layers/detection.py — multi_box_head,
bipartite_match, target_assign, detection_output, ssd_loss, iou_similarity,
box_coder, prior_box. Ground-truth inputs are lod_level-1 data layers
(padded [B, G, ...] + lengths in this framework).

ssd_loss lowers to ONE fused op (ops/detection_ops.py _ssd_loss) computing
the same composition the reference builds from ~10 ops; the individual ops
are also registered for direct use. detection_map is provided host-side as
metrics.DetectionMAP (the reference's detection_map op is a CPU-only
accumulator; a host metric is the TPU-native equivalent).
"""
from ..core.layer_helper import LayerHelper
from ..core.framework import Variable
from .sequence import _seq_len
from . import tensor

__all__ = [
    "prior_box", "iou_similarity", "box_coder", "bipartite_match",
    "target_assign", "ssd_loss", "detection_output", "multi_box_head",
    "detection_map",
]


def detection_map(detect_res, label, class_num=None, background_label=0,
                  overlap_threshold=0.5, evaluate_difficult=True,
                  ap_version="integral"):
    """Batch mAP of detection_output results against ground truth.

    Parity: reference detection_map_op.h (score-sorted greedy TP/FP at an
    IoU threshold, 11point/integral AP) — a CPU-only op there; here the
    same numpy routine (metrics.DetectionMAP) runs as a host callback
    inside the jitted program, so the fetch is a plain scalar.

    detect_res: [B, K, 6] (-1 padded) + lengths companion, as produced by
    detection_output. label: lod_level-1 ground truth [B, G, 5] rows of
    (class, x1, y1, x2, y2), or [B, G, 6] with a difficult flag after the
    class — with evaluate_difficult=False, difficult boxes don't count as
    positives and detections matching them are ignored (reference VOC
    protocol). background_label (when not None) is excluded from the AP
    mean; class_num is accepted for signature parity. Returns [1] float32
    mAP."""
    helper = LayerHelper("detection_map", **locals())
    out = helper.create_variable_for_type_inference("float32")
    out.stop_gradient = True
    out.shape = (1,)
    helper.append_op(
        type="detection_map",
        inputs={"DetectRes": [detect_res],
                "DetectLen": [helper.block.var_recursive(
                    detect_res.seq_len_var)],
                "Label": [label],
                "LabelLen": [helper.block.var_recursive(label.seq_len_var)]},
        outputs={"Out": [out]},
        attrs={"overlap_threshold": float(overlap_threshold),
               "evaluate_difficult": bool(evaluate_difficult),
               "background_label": background_label,
               "ap_version": str(ap_version)},
        infer_shape=False)
    return out


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=None, offset=0.5, name=None):
    """Generate SSD prior boxes for one feature map (prior_box_op.h)."""
    helper = LayerHelper("prior_box", **locals())
    boxes = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    steps = steps or [0.0, 0.0]
    helper.append_op(
        type="prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [variances]},
        attrs={"min_sizes": list(min_sizes),
               "max_sizes": list(max_sizes or []),
               "aspect_ratios": list(aspect_ratios or [1.0]),
               "variances": list(variance), "flip": flip, "clip": clip,
               "step_w": steps[0], "step_h": steps[1], "offset": offset})
    boxes.stop_gradient = True
    variances.stop_gradient = True
    return boxes, variances


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None):
    helper = LayerHelper("box_coder", **locals())
    out = helper.create_variable_for_type_inference(prior_box.dtype)
    helper.append_op(
        type="box_coder",
        inputs={"PriorBox": [prior_box], "PriorBoxVar": [prior_box_var],
                "TargetBox": [target_box]},
        outputs={"OutputBox": [out]},
        attrs={"code_type": code_type, "box_normalized": box_normalized})
    return out


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """dist_matrix: lod_level-1 [B, G, M] (gt rows per image)."""
    helper = LayerHelper("bipartite_match", **locals())
    match_indices = helper.create_variable_for_type_inference("int32")
    match_distance = helper.create_variable_for_type_inference(
        dist_matrix.dtype)
    helper.append_op(
        type="bipartite_match",
        inputs={"DistMat": [dist_matrix],
                "GtLen": [_seq_len(helper, dist_matrix)]},
        outputs={"ColToRowMatchIndices": [match_indices],
                 "ColToRowMatchDist": [match_distance]},
        attrs={"match_type": match_type or "bipartite",
               "dist_threshold": dist_threshold or 0.5})
    for v in (match_indices, match_distance):
        v.lod_level = 0
        v.seq_len_var = None
        v.stop_gradient = True
    return match_indices, match_distance


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    helper = LayerHelper("target_assign", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out_weight = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="target_assign",
        inputs={"X": [input], "MatchIndices": [matched_indices]},
        outputs={"Out": [out], "OutWeight": [out_weight]},
        attrs={"mismatch_value": mismatch_value or 0})
    for v in (out, out_weight):
        v.lod_level = 0
        v.seq_len_var = None
        v.stop_gradient = True
    return out, out_weight


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None):
    """SSD multibox loss -> [batch, 1] (detection.py:348).

    Single fused op; see ops/detection_ops.py _ssd_loss for the exact
    composition parity."""
    helper = LayerHelper("ssd_loss", **locals())
    if mining_type != "max_negative":
        raise ValueError("Only support mining_type == max_negative now.")
    loss = helper.create_variable_for_type_inference(location.dtype)
    inputs = {"Location": [location], "Confidence": [confidence],
              "GtBox": [gt_box], "GtLabel": [gt_label],
              "GtLen": [_seq_len(helper, gt_box)],
              "PriorBox": [prior_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        type="ssd_loss",
        inputs=inputs,
        outputs={"Loss": [loss]},
        attrs={"background_label": background_label,
               "overlap_threshold": overlap_threshold,
               "neg_pos_ratio": neg_pos_ratio, "neg_overlap": neg_overlap,
               "loc_loss_weight": loc_loss_weight,
               "conf_loss_weight": conf_loss_weight,
               "match_type": match_type, "normalize": normalize})
    loss.lod_level = 0
    loss.seq_len_var = None
    loss.shape = (-1, 1)
    return loss


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """Decode + multiclass NMS -> [B, keep_top_k, 6] (-1 padded) + lengths.

    Parity: detection.py:46 (box_coder decode + softmax + multiclass_nms).
    The reference returns a LoD [total_kept, 6]; here the dense padded
    equivalent with a @SEQLEN companion."""
    from . import nn
    helper = LayerHelper("detection_output", **locals())
    decoded_box = box_coder(
        prior_box=prior_box, prior_box_var=prior_box_var, target_box=loc,
        code_type="decode_center_size")
    scores = nn.softmax(input=scores)
    scores = nn.transpose(scores, perm=[0, 2, 1])
    scores.stop_gradient = True

    out = helper.create_variable_for_type_inference(loc.dtype)
    out_len = helper.block.create_var(
        name=out.name + "@SEQLEN", shape=[-1], dtype="int32",
        stop_gradient=True)
    helper.append_op(
        type="multiclass_nms",
        inputs={"BBoxes": [decoded_box], "Scores": [scores]},
        outputs={"Out": [out], "OutLen": [out_len]},
        attrs={"background_label": background_label,
               "nms_threshold": nms_threshold, "nms_top_k": nms_top_k,
               "keep_top_k": keep_top_k, "score_threshold": score_threshold,
               "nms_eta": nms_eta})
    out.lod_level = 1
    out.seq_len_var = out_len.name
    out.stop_gradient = True
    return out


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None):
    """SSD head over multiple feature maps (detection.py:566).

    Returns (mbox_locs [B, M, 4], mbox_confs [B, M, C], boxes [M, 4],
    variances [M, 4])."""
    from . import nn
    from . import ops as _ops

    n = len(inputs)
    if min_sizes is None:
        assert min_ratio is not None and max_ratio is not None
        min_sizes, max_sizes = [], []
        step = int((max_ratio - min_ratio) / (n - 2)) if n > 2 else 0
        for ratio in range(min_ratio, max_ratio + 1, max(step, 1)):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes
    if not isinstance(aspect_ratios[0], (list, tuple)):
        aspect_ratios = [aspect_ratios] * n

    mbox_locs, mbox_confs, box_list, var_list = [], [], [], []
    for i, input in enumerate(inputs):
        min_s = min_sizes[i]
        max_s = max_sizes[i] if max_sizes else None
        min_s = min_s if isinstance(min_s, (list, tuple)) else [min_s]
        max_s = (max_s if isinstance(max_s, (list, tuple)) else [max_s]) \
            if max_s is not None else []
        step = steps[i] if steps else [step_w[i] if step_w else 0.0,
                                       step_h[i] if step_h else 0.0]
        box, var = prior_box(
            input, image, min_s, max_s, aspect_ratios[i], variance, flip,
            clip, step if isinstance(step, (list, tuple)) else [step, step],
            offset)
        from ..ops.detection_ops import _expand_aspect_ratios
        expanded = _expand_aspect_ratios(aspect_ratios[i], flip)
        n_non_unit = sum(1 for a in expanded if abs(a - 1.0) > 1e-6)
        # per min_size: ar=1 prior (+ max prior) + one per non-unit ratio
        num_priors = len(min_s) * (1 + n_non_unit) + \
            (len(max_s) if max_s else 0)

        loc = nn.conv2d(input=input, num_filters=num_priors * 4,
                        filter_size=kernel_size, padding=pad, stride=stride)
        loc = nn.transpose(loc, perm=[0, 2, 3, 1])
        loc = _ops.reshape(x=loc, shape=[0, -1, 4])
        mbox_locs.append(loc)

        conf = nn.conv2d(input=input, num_filters=num_priors * num_classes,
                         filter_size=kernel_size, padding=pad, stride=stride)
        conf = nn.transpose(conf, perm=[0, 2, 3, 1])
        conf = _ops.reshape(x=conf, shape=[0, -1, num_classes])
        mbox_confs.append(conf)

        box_list.append(_ops.reshape(x=box, shape=[-1, 4]))
        var_list.append(_ops.reshape(x=var, shape=[-1, 4]))

    mbox_locs_concat = tensor.concat(mbox_locs, axis=1)
    mbox_confs_concat = tensor.concat(mbox_confs, axis=1)
    box_concat = tensor.concat(box_list, axis=0)
    var_concat = tensor.concat(var_list, axis=0)
    for v in (box_concat, var_concat):
        v.stop_gradient = True
    return mbox_locs_concat, mbox_confs_concat, box_concat, var_concat
