"""DataFeeder: convert python/numpy rows into feed tensors.

Parity: python/paddle/fluid/data_feeder.py — converts a minibatch (list of
tuples from a reader) into {var_name: array-or-LoDTensor} keyed by the feed
list, handling lod_level>0 vars by building LoDTensors from per-row lists.
"""
import numpy as np

from .core.framework import Variable, default_main_program, convert_dtype
from .core.lod import LoDTensor


class DataFeeder(object):
    def __init__(self, feed_list, place=None, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        if program is None:
            program = default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block().var(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError("feed_list should contain Variables or names")
            self.feed_names.append(each_var.name)
            self.feed_lod_level.append(each_var.lod_level)
            self.feed_shapes.append(each_var.shape)
            self.feed_dtypes.append(each_var.dtype)
        self.place = place

    def feed(self, iterable):
        rows = list(iterable)
        out = {}
        for i, name in enumerate(self.feed_names):
            cols = [row[i] for row in rows]
            lod_level = self.feed_lod_level[i]
            dtype = convert_dtype(self.feed_dtypes[i])
            if lod_level == 0:
                arr = np.asarray(cols, dtype=dtype)
                shape = self.feed_shapes[i]
                if shape is not None:
                    # reshape flat rows into declared shape (batch dim -1)
                    want = [d for d in shape]
                    if want and want[0] == -1:
                        arr = arr.reshape([len(rows)] +
                                          [d for d in want[1:]])
                out[name] = arr
            else:
                seqs = [np.asarray(c, dtype=dtype) for c in cols]
                seqs = [s.reshape(-1, *self._feat_shape(i)) for s in seqs]
                out[name] = LoDTensor.from_sequences(seqs, dtype=dtype)
        return out

    def _feat_shape(self, i):
        shape = self.feed_shapes[i]
        if shape is None:
            return ()
        return tuple(d for d in shape if d != -1) or (1,)
