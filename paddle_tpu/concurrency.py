"""fluid.concurrency surface (parity: python/paddle/fluid/concurrency.py).

EXPLICIT SCOPE CUT (SURVEY.md §2): the reference's Go-style CSP channels
(make_channel/channel_send/channel_recv/channel_close/Select) block
interpreter threads between ops — semantics that contradict whole-program
XLA execution and that had no model, test, or benchmark user in the
reference era. The TPU-native equivalents of their use cases are the async
reader layers (fluid.layers.double_buffer) for producer/consumer input and
collective-based parallelism (ParallelExecutor) for coordination. The names
exist so reference scripts fail with a curated, actionable error instead of
an AttributeError.
"""
from .layers.control_flow import Select  # noqa: F401

__all__ = ["make_channel", "channel_send", "channel_recv", "channel_close",
           "Select"]

_MSG = ("fluid.concurrency is not rebuilt in paddle_tpu (explicit scope "
        "cut, SURVEY.md §2): CSP channel ops block host threads between "
        "ops, which contradicts whole-program XLA execution. Use the "
        "reader layers (fluid.layers.double_buffer) for async input, or "
        "ParallelExecutor collectives for parallel coordination.")


def make_channel(dtype, capacity=0):
    raise NotImplementedError(_MSG)


def channel_send(channel, value, is_copy=False):
    raise NotImplementedError(_MSG)


def channel_recv(channel, return_value):
    raise NotImplementedError(_MSG)


def channel_close(channel):
    raise NotImplementedError(_MSG)
