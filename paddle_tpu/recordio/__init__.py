"""recordio: chunked record files for the data pipeline.

Parity: paddle/fluid/recordio/{writer,scanner,chunk,header} + the
python/paddle/fluid/recordio_writer.py surface. Wire format is identical to
the reference (see native/recordio.cc header comment). The fast path is the
C++ library via ctypes; the pure-Python implementation below produces
byte-identical files and is used when no toolchain is available — both are
covered by the same round-trip tests.

Compressor codes match the reference enum: 0 none, 1 snappy (not built),
2 gzip (zlib).
"""
import ctypes
import struct
import zlib

from ..native import load_library

__all__ = ["Writer", "Scanner", "Compressor", "write_records",
           "read_records"]

_MAGIC = 0x01020304


class Compressor(object):
    NoCompress = 0
    Snappy = 1
    Gzip = 2


def _native():
    lib = load_library("recordio")
    if lib is None:
        return None
    try:
        lib.rio_writer_open.restype = ctypes.c_void_p
        lib.rio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                        ctypes.c_uint32, ctypes.c_uint64]
        lib.rio_writer_write.restype = ctypes.c_int
        lib.rio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_uint32]
        lib.rio_writer_close.restype = ctypes.c_int
        lib.rio_writer_close.argtypes = [ctypes.c_void_p]
        lib.rio_scanner_open.restype = ctypes.c_void_p
        lib.rio_scanner_open.argtypes = [ctypes.c_char_p]
        lib.rio_scanner_next.restype = ctypes.c_int
        lib.rio_scanner_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint32)]
        lib.rio_scanner_close.restype = None
        lib.rio_scanner_close.argtypes = [ctypes.c_void_p]
        return lib
    except Exception:
        return None


class Writer(object):
    """Append records (bytes) to a recordio file, chunked + checksummed."""

    def __init__(self, path, compressor=Compressor.NoCompress,
                 max_num_records=1000, max_chunk_bytes=1 << 20,
                 use_native=True):
        self._compressor = compressor
        self._lib = _native() if use_native else None
        if self._lib is not None:
            self._h = self._lib.rio_writer_open(
                path.encode(), compressor, max_num_records, max_chunk_bytes)
            if not self._h:
                raise IOError("cannot open %r for writing" % path)
        else:
            self._f = open(path, "wb")
            self._records = []
            self._nbytes = 0
            self._max_records = max_num_records
            self._max_bytes = max_chunk_bytes

    def write(self, record):
        if isinstance(record, str):
            record = record.encode("utf-8")
        if self._lib is not None:
            if self._lib.rio_writer_write(self._h, record,
                                          len(record)) != 0:
                raise IOError("recordio write failed")
            return
        self._records.append(bytes(record))
        # +4: count the length prefix too, exactly like the native writer,
        # so both implementations flush chunks at identical points
        self._nbytes += len(record) + 4
        if len(self._records) >= self._max_records or \
                self._nbytes >= self._max_bytes:
            self._flush()

    def _flush(self):
        if not self._records:
            return
        payload = b"".join(struct.pack("<I", len(r)) + r
                           for r in self._records)
        comp = self._compressor
        data = payload
        if comp == Compressor.Gzip:
            data = zlib.compress(payload)
        elif comp != Compressor.NoCompress:
            raise NotImplementedError("snappy not built")
        crc = zlib.crc32(data) & 0xFFFFFFFF
        self._f.write(struct.pack("<5I", _MAGIC, len(self._records), crc,
                                  comp, len(data)))
        self._f.write(data)
        self._records = []
        self._nbytes = 0

    def close(self):
        if self._lib is not None:
            if self._h is not None:
                if self._lib.rio_writer_close(self._h) != 0:
                    self._h = None
                    raise IOError("recordio close/flush failed")
                self._h = None
            return
        self._flush()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Scanner(object):
    """Iterate records (bytes) of a recordio file; validates checksums."""

    def __init__(self, path, use_native=True):
        self._lib = _native() if use_native else None
        if self._lib is not None:
            self._h = self._lib.rio_scanner_open(path.encode())
            if not self._h:
                raise IOError("cannot open %r" % path)
        else:
            self._f = open(path, "rb")
            self._chunk = []
            self._idx = 0

    def __iter__(self):
        return self

    def _load_chunk_py(self):
        hdr = self._f.read(20)
        if len(hdr) == 0:
            return False
        if len(hdr) < 20:
            raise IOError("truncated recordio header")
        magic, num, crc, comp, size = struct.unpack("<5I", hdr)
        if magic != _MAGIC:
            raise IOError("bad recordio magic %x" % magic)
        data = self._f.read(size)
        if len(data) != size:
            raise IOError("truncated recordio chunk")
        if zlib.crc32(data) & 0xFFFFFFFF != crc:
            raise IOError("recordio checksum mismatch")
        if comp == Compressor.Gzip:
            data = zlib.decompress(data)
        elif comp != Compressor.NoCompress:
            raise NotImplementedError("compressor %d" % comp)
        self._chunk = []
        pos = 0
        for _ in range(num):
            (n,) = struct.unpack_from("<I", data, pos)
            pos += 4
            self._chunk.append(data[pos:pos + n])
            pos += n
        self._idx = 0
        return True

    def __next__(self):
        if self._lib is not None:
            data = ctypes.POINTER(ctypes.c_uint8)()
            n = ctypes.c_uint32()
            rc = self._lib.rio_scanner_next(self._h, ctypes.byref(data),
                                            ctypes.byref(n))
            if rc == 0:
                raise StopIteration
            if rc < 0:
                raise IOError("corrupt recordio file")
            return ctypes.string_at(data, n.value)
        while self._idx >= len(self._chunk):
            if not self._load_chunk_py():
                raise StopIteration
        r = self._chunk[self._idx]
        self._idx += 1
        return r

    next = __next__

    def close(self):
        if self._lib is not None:
            if self._h is not None:
                self._lib.rio_scanner_close(self._h)
                self._h = None
            return
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_records(path, records, **kwargs):
    with Writer(path, **kwargs) as w:
        for r in records:
            w.write(r)


def read_records(path, **kwargs):
    with Scanner(path, **kwargs) as s:
        return list(s)
