"""All-to-all (DeepSpeed-Ulysses-style) sequence/context parallelism.

The complement of ring attention (SURVEY.md §2 long-context: "ring
attention or all-to-all sequence/context parallelism"): instead of rotating
K/V blocks around the `sp` ring, ONE all_to_all over ICI re-shards the
activations from sequence-sharded [B, T/sp, H, D] to head-sharded
[B, T, H/sp, D]; every chip then runs plain dense attention over the FULL
sequence for its head group, and a final all_to_all restores the sequence
sharding. Four all_to_all ops total per attention (q/k/v in, output back)
in two communication phases (vs sp-1 ppermute hops for the ring) at the
cost of requiring heads % sp == 0 — the standard trade: Ulysses when heads
are plentiful, ring when sequence is extreme.

The reference (March 2018) has no attention parallelism; this is TPU-first
design, not parity.
"""
import functools

from jax import lax
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from .ring_attention import attention_reference, sp_spec_for_mesh

__all__ = ["ulysses_attention", "ulysses_attention_sharded"]


def ulysses_attention(q, k, v, axis_name, causal=False, scale=None):
    """Per-shard body (use inside shard_map): q/k/v are the local
    sequence shards [B, T/sp, H, D]; heads must divide by the axis size."""
    sp = lax.axis_size(axis_name) if hasattr(lax, "axis_size") \
        else lax.psum(1, axis_name)
    h = q.shape[2]
    if h % sp != 0:
        raise ValueError(
            "ulysses_attention needs heads %% sp == 0 (got %d heads over "
            "sp=%d); use ring_attention for head-scarce long-context" %
            (h, sp))

    def seq_to_heads(x):
        # [B, T/sp, H, D] -> [B, T, H/sp, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = attention_reference(qh, kh, vh, causal=causal, scale=scale)
    return heads_to_seq(out)


def ulysses_attention_sharded(q, k, v, mesh, causal=False, scale=None,
                              batch_axis="dp", seq_axis="sp"):
    """Global-view entry: full (or GSPMD-sharded) [B, T, H, D] arrays;
    shard_map splits over (dp, sp) and runs the all-to-all attention."""
    spec, _ = sp_spec_for_mesh(mesh, batch_axis, seq_axis)
    fn = shard_map(
        functools.partial(ulysses_attention, axis_name=seq_axis,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
