"""All-to-all (DeepSpeed-Ulysses-style) sequence/context parallelism.

The complement of ring attention (SURVEY.md §2 long-context: "ring
attention or all-to-all sequence/context parallelism"): instead of rotating
K/V blocks around the `sp` ring, ONE all_to_all over ICI re-shards the
activations from sequence-sharded [B, T/sp, H, D] to head-sharded
[B, T, H/sp, D]; every chip then runs plain dense attention over the FULL
sequence for its head group, and a final all_to_all restores the sequence
sharding. Four all_to_all ops total per attention (q/k/v in, output back)
in two communication phases (vs sp-1 ppermute hops for the ring) at the
cost of requiring heads % sp == 0 — the standard trade: Ulysses when heads
are plentiful, ring when sequence is extreme.

The reference (March 2018) has no attention parallelism; this is TPU-first
design, not parity.
"""
from jax import lax

from .ring_attention import attention_reference, sp_shard_call

__all__ = ["ulysses_attention", "ulysses_attention_sharded"]


def ulysses_attention(q, k, v, axis_name, causal=False, scale=None,
                      kv_len=None):
    """Per-shard body (use inside shard_map): q/k/v are the local
    sequence shards [B, T/sp, H, D]; heads must divide by the axis size.
    kv_len: optional [B] true key lengths — after the all-to-all each
    shard holds the FULL sequence for its head slice, so key-padding is
    the plain dense mask."""
    sp = lax.axis_size(axis_name) if hasattr(lax, "axis_size") \
        else lax.psum(1, axis_name)
    h = q.shape[2]
    if h % sp != 0:
        raise ValueError(
            "ulysses_attention needs heads %% sp == 0 (got %d heads over "
            "sp=%d); use ring_attention for head-scarce long-context" %
            (h, sp))

    def seq_to_heads(x):
        # [B, T/sp, H, D] -> [B, T, H/sp, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = attention_reference(qh, kh, vh, causal=causal, scale=scale,
                              kv_len=kv_len)
    return heads_to_seq(out)


def ulysses_attention_sharded(q, k, v, mesh, causal=False, scale=None,
                              batch_axis="dp", seq_axis="sp", kv_len=None):
    """Global-view entry: full (or GSPMD-sharded) [B, T, H, D] arrays;
    shard_map splits over (dp, sp) and runs the all-to-all attention.
    kv_len: optional [B] int32 global true key lengths (sharded over the
    batch axis like q's batch dim)."""
    def body(qs, ks, vs, lens):
        return ulysses_attention(qs, ks, vs, axis_name=seq_axis,
                                 causal=causal, scale=scale, kv_len=lens)

    return sp_shard_call(body, q, k, v, mesh, batch_axis, seq_axis, kv_len)
