"""Multi-device / multi-host parallelism over jax.sharding Meshes."""
from .mesh import make_mesh, data_parallel_mesh, replicated, batch_sharded, \
    Mesh, NamedSharding, P
from .parallel_executor import ParallelExecutor
from .plan import ShardingPlan, VarPlan
from .ring_attention import ring_attention, ring_attention_sharded, \
    attention_reference, sequence_parallel_specs
from .pipeline import pipeline_apply, pipeline_stages_spec, \
    stack_stage_params, sequential_reference, mlp_block_init, \
    mlp_block_apply, mlp_block_specs
from .distributed import init_distributed, shutdown_distributed, \
    global_mesh, DeviceLayout, active_layout, set_active_layout, \
    is_initialized as distributed_is_initialized
from .moe import moe_layer, init_moe_params, moe_param_specs
from .ulysses import ulysses_attention, ulysses_attention_sharded
# (the seed-era `parallel.tp` module is gone: Program-level tensor
# parallelism is ShardingPlan.build(tp_axis=...) — plan.py,
# ARCHITECTURE.md §23 — and the surviving Megatron stage block lives in
# pipeline.py. See MIGRATION.md.)
