"""Multi-device / multi-host parallelism over jax.sharding Meshes."""
