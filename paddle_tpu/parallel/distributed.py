"""Multi-host runtime glue: jax.distributed over the reference's cluster
environment contract.

Parity: the reference's multi-node story is env-var driven k8s jobs
(`benchmark/cluster/vgg16/fluid_trainer.yaml`: TRAINING_ROLE / TRAINERS /
PSERVERS + `paddle/scripts/cluster_train` discovery) feeding the pserver
ring built by distribute_transpiler. TPU-native multi-host needs none of
the pserver machinery — every host runs the SAME SPMD program, and this
module's job is just to (a) form the jax.distributed process group from the
cluster env and (b) hand back a GLOBAL mesh spanning every chip on every
host, so the one-process ParallelExecutor/pipeline/ring-attention code
works unchanged at multi-host scale (collectives ride ICI within a slice
and DCN across, inserted by XLA from the same shardings).

Env contract (reference names first, jax-standard fallbacks):
  TRAINERS / num_processes        — number of host processes in the job
  TRAINER_ID / process_id         — this process's rank
  PADDLE_COORDINATOR / coordinator_address — "host:port" of rank 0

Elastic rescale (resilience/cluster.py) additionally needs the runtime to
be RE-initializable in one process: `shutdown_distributed()` tears down
the client AND drops every piece of cached mesh/device state this module
holds (the active `DeviceLayout`), so a worker can leave a 2-host cohort
and re-join a 1-host one without leaking the old world's shape into the
new mesh. `DeviceLayout` is the explicit description of one cohort shape
(process count, rank, local device count, mesh axes) — the thing a
checkpoint records at save and `CheckpointManager.restore(layout=)`
reshards onto.
"""
import os

import jax

from .mesh import make_mesh, Mesh

__all__ = ["init_distributed", "is_initialized", "shutdown_distributed",
           "global_mesh", "process_count", "process_index",
           "local_device_count", "global_device_count",
           "DeviceLayout", "active_layout", "set_active_layout"]

# _noop: a single-host init_distributed() ran (nothing to rendezvous).
# _client: jax.distributed.initialize actually joined a process group.
# Kept separate so a later call WITH a coordinator still rendezvouses even
# after an early no-op init, and shutdown only tears down a real client.
_noop = False
_client = False
# the process's current cohort shape (elastic workers set it each
# generation); shutdown_distributed drops it — cached device state must
# not outlive the world it described
_layout = None


class DeviceLayout(object):
    """One cohort shape: `num_processes` host processes, this process at
    `process_index`, each using `local_device_count` of its devices with
    `mesh_axes` laid over them. JSON round-trips (checkpoint metadata,
    the cluster plan), and `local_mesh()` materializes the jax Mesh this
    process trains on — the restore-time resharding target.

    `shard_axis` names the mesh axis the ShardingPlan splits the weight
    update over (params + optimizer accumulators, parallel/plan.py).
    None (the default) means update state follows `batch_axis` — the
    standard ZeRO-over-dp layout; a distinct axis (e.g. a dp×zero mesh)
    is named explicitly. Serialized in to_json/from_json so a snapshot
    records which axis its sharded update state was split over and a
    resharding restore (checkpoint/manager.py `_adapt_spec`) can drop or
    re-divide that axis on the target layout's mesh."""

    __slots__ = ("num_processes", "process_index", "local_device_count",
                 "mesh_axes", "batch_axis", "shard_axis",
                 "skip_local_devices")

    def __init__(self, num_processes=1, process_index=0,
                 local_device_count=None, mesh_axes=None, batch_axis="dp",
                 shard_axis=None, skip_local_devices=None):
        self.num_processes = int(num_processes)
        self.process_index = int(process_index)
        if not (0 <= self.process_index < self.num_processes):
            raise ValueError(
                "process_index %d outside [0, %d)" % (self.process_index,
                                                      self.num_processes))
        self.local_device_count = (None if local_device_count is None
                                   else int(local_device_count))
        self.mesh_axes = dict(mesh_axes) if mesh_axes else {batch_axis: -1}
        self.batch_axis = batch_axis
        if shard_axis is not None and shard_axis not in self.mesh_axes:
            raise ValueError(
                "shard_axis %r is not one of the layout's mesh axes %r"
                % (shard_axis, sorted(self.mesh_axes)))
        self.shard_axis = shard_axis
        # local device indices this process must NOT use — the cluster
        # coordinator's per-device QUARANTINE list (a chip the SDC
        # canary convicted, resilience/sdc.py): the local mesh is built
        # from the remaining devices, so a resharded generation trains
        # around the bad chip without dropping the whole host
        self.skip_local_devices = tuple(
            sorted(set(int(i) for i in (skip_local_devices or ()))))

    @property
    def total_device_count(self):
        """Cluster-wide chip count (None until local count is resolved)."""
        if self.local_device_count is None:
            return None
        return self.num_processes * self.local_device_count

    def resolved_local_device_count(self):
        return (self.local_device_count if self.local_device_count
                is not None
                else len(jax.devices()) - len(self.skip_local_devices))

    def local_devices(self):
        """This process's usable devices in index order — every live
        device minus the quarantined indices. The canary checker and
        `local_mesh()` draw from the same list, so a convicted chip is
        neither trained on nor re-canaried."""
        skip = set(self.skip_local_devices)
        return [d for i, d in enumerate(jax.devices()) if i not in skip]

    def local_mesh(self):
        """The Mesh over this process's slice of devices. With fewer
        live (non-quarantined) devices than the layout asks for, raises
        — a silent smaller mesh would break the cohort's divisibility
        contract."""
        want = self.resolved_local_device_count()
        devices = self.local_devices()
        if len(devices) < want or want < 1:
            raise ValueError(
                "DeviceLayout wants %d local devices but only %d usable "
                "(%d quarantined) "
                "(XLA_FLAGS=--xla_force_host_platform_device_count=%d "
                "for a virtual CPU mesh)"
                % (want, len(devices), len(self.skip_local_devices),
                   max(1, want)))
        return make_mesh(self.mesh_axes, devices[:want])

    def resolved_shard_axis(self):
        """The axis update-state sharding uses: `shard_axis` when named,
        else the batch axis (ZeRO-over-dp default)."""
        return self.shard_axis if self.shard_axis is not None \
            else self.batch_axis

    def to_json(self):
        out = {"num_processes": self.num_processes,
               "process_index": self.process_index,
               "local_device_count": self.local_device_count,
               "mesh_axes": dict(self.mesh_axes),
               "batch_axis": self.batch_axis,
               "shard_axis": self.shard_axis}
        if self.skip_local_devices:
            out["skip_local_devices"] = list(self.skip_local_devices)
        return out

    @classmethod
    def from_json(cls, d):
        return cls(num_processes=d.get("num_processes", 1),
                   process_index=d.get("process_index", 0),
                   local_device_count=d.get("local_device_count"),
                   mesh_axes=d.get("mesh_axes"),
                   batch_axis=d.get("batch_axis", "dp"),
                   shard_axis=d.get("shard_axis"),
                   skip_local_devices=d.get("skip_local_devices"))

    def __eq__(self, other):
        return isinstance(other, DeviceLayout) \
            and self.to_json() == other.to_json()

    def __ne__(self, other):
        return not self.__eq__(other)

    def __repr__(self):
        return ("DeviceLayout(procs=%d, rank=%d, local_devices=%s, "
                "axes=%r%s%s)" % (
                    self.num_processes, self.process_index,
                    self.local_device_count, self.mesh_axes,
                    ", shard_axis=%r" % self.shard_axis
                    if self.shard_axis is not None else "",
                    ", quarantined=%r" % list(self.skip_local_devices)
                    if self.skip_local_devices else ""))


def active_layout():
    """The cohort shape this process currently trains under, or None.
    Elastic workers set it each generation; plain single-host jobs never
    need to."""
    return _layout


def set_active_layout(layout):
    """Install `layout` (a DeviceLayout or None) as the process's
    current cohort shape; returns the previous one."""
    global _layout
    if layout is not None and not isinstance(layout, DeviceLayout):
        raise TypeError("set_active_layout wants a DeviceLayout or None, "
                        "got %r" % (layout,))
    old = _layout
    _layout = layout
    return old


def _env_int(*names):
    for n in names:
        v = os.environ.get(n)
        if v not in (None, ""):
            return int(v)
    return None


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None, local_device_ids=None):
    """Join the multi-host process group (no-op for single-process jobs).

    Arguments fall back to the env contract above. Call once per host
    process before any jax device use; after it, jax.devices() is GLOBAL
    (all chips of all hosts) and `global_mesh` can span the pod.

    Re-initialization: after `shutdown_distributed()` a fresh call joins
    a NEW process group (possibly with a different world size/rank) —
    the elastic-rescale entry point. A call while a client is live stays
    a no-op returning False, as before.
    """
    global _noop, _client
    if _client:
        return False
    coordinator_address = coordinator_address or \
        os.environ.get("PADDLE_COORDINATOR") or \
        os.environ.get("JAX_COORDINATOR_ADDRESS")
    num_processes = num_processes if num_processes is not None else \
        _env_int("TRAINERS", "JAX_NUM_PROCESSES")
    process_id = process_id if process_id is not None else \
        _env_int("TRAINER_ID", "JAX_PROCESS_ID")

    if not coordinator_address and (num_processes in (None, 1)):
        # single-host run: nothing to initialize, jax.devices() is already
        # the whole world (a later call WITH a coordinator still works)
        _noop = True
        return False

    if not coordinator_address:
        raise ValueError(
            "multi-process job (TRAINERS=%r) needs a coordinator: set "
            "PADDLE_COORDINATOR=host:port of rank 0 (or pass "
            "coordinator_address)" % (num_processes,))
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    _client = True
    return True


def is_initialized():
    return _noop or _client


def shutdown_distributed():
    """Leave the process group and DROP all cached mesh/device state
    (the active DeviceLayout) — after this, `init_distributed` can form
    a new, differently-shaped world in the same process. Idempotent."""
    global _noop, _client, _layout
    if _client:
        jax.distributed.shutdown()
        _client = False
    _noop = False
    _layout = None


def process_count():
    return jax.process_count()


def process_index():
    return jax.process_index()


def local_device_count():
    return jax.local_device_count()


def global_device_count():
    return jax.device_count()


def global_mesh(axes=None, devices=None):
    """A Mesh over every chip of every host.

    axes: dict axis -> size with at most one -1 wildcard (default
    {'dp': -1}, pure data parallel). Lay the fastest-varying (model/tensor)
    axes innermost so their collectives stay on intra-host ICI; the leading
    dp axis then crosses hosts over DCN — the standard pod layout."""
    axes = axes or {"dp": -1}
    devices = devices if devices is not None else jax.devices()
    return make_mesh(axes, devices)
