"""Multi-host runtime glue: jax.distributed over the reference's cluster
environment contract.

Parity: the reference's multi-node story is env-var driven k8s jobs
(`benchmark/cluster/vgg16/fluid_trainer.yaml`: TRAINING_ROLE / TRAINERS /
PSERVERS + `paddle/scripts/cluster_train` discovery) feeding the pserver
ring built by distribute_transpiler. TPU-native multi-host needs none of
the pserver machinery — every host runs the SAME SPMD program, and this
module's job is just to (a) form the jax.distributed process group from the
cluster env and (b) hand back a GLOBAL mesh spanning every chip on every
host, so the one-process ParallelExecutor/pipeline/ring-attention code
works unchanged at multi-host scale (collectives ride ICI within a slice
and DCN across, inserted by XLA from the same shardings).

Env contract (reference names first, jax-standard fallbacks):
  TRAINERS / num_processes        — number of host processes in the job
  TRAINER_ID / process_id         — this process's rank
  PADDLE_COORDINATOR / coordinator_address — "host:port" of rank 0
"""
import os

import jax

from .mesh import make_mesh, Mesh

__all__ = ["init_distributed", "is_initialized", "shutdown_distributed",
           "global_mesh", "process_count", "process_index",
           "local_device_count", "global_device_count"]

# _noop: a single-host init_distributed() ran (nothing to rendezvous).
# _client: jax.distributed.initialize actually joined a process group.
# Kept separate so a later call WITH a coordinator still rendezvouses even
# after an early no-op init, and shutdown only tears down a real client.
_noop = False
_client = False


def _env_int(*names):
    for n in names:
        v = os.environ.get(n)
        if v not in (None, ""):
            return int(v)
    return None


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None, local_device_ids=None):
    """Join the multi-host process group (no-op for single-process jobs).

    Arguments fall back to the env contract above. Call once per host
    process before any jax device use; after it, jax.devices() is GLOBAL
    (all chips of all hosts) and `global_mesh` can span the pod.
    """
    global _noop, _client
    if _client:
        return False
    coordinator_address = coordinator_address or \
        os.environ.get("PADDLE_COORDINATOR") or \
        os.environ.get("JAX_COORDINATOR_ADDRESS")
    num_processes = num_processes if num_processes is not None else \
        _env_int("TRAINERS", "JAX_NUM_PROCESSES")
    process_id = process_id if process_id is not None else \
        _env_int("TRAINER_ID", "JAX_PROCESS_ID")

    if not coordinator_address and (num_processes in (None, 1)):
        # single-host run: nothing to initialize, jax.devices() is already
        # the whole world (a later call WITH a coordinator still works)
        _noop = True
        return False

    if not coordinator_address:
        raise ValueError(
            "multi-process job (TRAINERS=%r) needs a coordinator: set "
            "PADDLE_COORDINATOR=host:port of rank 0 (or pass "
            "coordinator_address)" % (num_processes,))
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    _client = True
    return True


def is_initialized():
    return _noop or _client


def shutdown_distributed():
    global _noop, _client
    if _client:
        jax.distributed.shutdown()
        _client = False
    _noop = False


def process_count():
    return jax.process_count()


def process_index():
    return jax.process_index()


def local_device_count():
    return jax.local_device_count()


def global_device_count():
    return jax.device_count()


def global_mesh(axes=None, devices=None):
    """A Mesh over every chip of every host.

    axes: dict axis -> size with at most one -1 wildcard (default
    {'dp': -1}, pure data parallel). Lay the fastest-varying (model/tensor)
    axes innermost so their collectives stay on intra-host ICI; the leading
    dp axis then crosses hosts over DCN — the standard pod layout."""
    axes = axes or {"dp": -1}
    devices = devices if devices is not None else jax.devices()
    return make_mesh(axes, devices)
