"""Device mesh helpers.

The reference scales with NCCL allreduce (paddle/fluid/framework/details/
nccl_all_reduce_op_handle.cc) and pserver send/recv. TPU-native scaling is
declarative: build a jax.sharding.Mesh over the chips and annotate shardings;
XLA GSPMD inserts all-reduce/all-gather/reduce-scatter over ICI.

Axis conventions used across paddle_tpu:
  dp — data parallel (batch dim)
  mp — model/tensor parallel (hidden dims)
  sp — sequence/context parallel (long sequences; ring attention)
  pp — pipeline stages
"""
import numpy as np

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "data_parallel_mesh", "replicated", "batch_sharded",
           "vary", "Mesh", "NamedSharding", "P"]


def vary(x, axes):
    """Mark a constant as device-varying over `axes` so shard_map loop
    carries type-check (jax version compat: pcast on newest jax, pvary
    on 0.5/0.6). JAX <= 0.4.x predates the varying-manual-axes type
    system entirely — there the annotation is meaningless and identity
    is the correct no-op. Shared by ring_attention and pipeline."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, tuple(axes), to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, tuple(axes))
    return x


def device_count():
    return len(jax.devices())


def make_mesh(axes, devices=None):
    """axes: dict axis_name -> size (use -1 once for 'remaining devices')."""
    devices = devices if devices is not None else jax.devices()
    import numbers
    try:
        sizes = {k: int(v) for k, v in dict(axes).items()
                 if isinstance(v, numbers.Integral)}
        ok = len(sizes) == len(dict(axes))
    except (TypeError, ValueError):
        ok = False
    if not ok:
        raise TypeError(
            "make_mesh expects {axis_name: size} (e.g. {'dp': -1} or "
            "{'dp': 4, 'mp': 2}), got %r" % (axes,))
    if any(s < 1 and s != -1 for s in sizes.values()) \
            or list(sizes.values()).count(-1) > 1:
        raise ValueError("make_mesh: axis sizes must be positive, with at "
                         "most one -1 wildcard; got %r" % (axes,))
    known = int(np.prod([s for s in sizes.values() if s != -1]))
    if any(v == -1 for v in sizes.values()) and known > len(devices):
        raise ValueError(
            "make_mesh: fixed axes in %r already need %d devices but only "
            "%d are available, leaving none for the -1 wildcard"
            % (axes, known, len(devices)))
    for k, v in sizes.items():
        if v == -1:
            sizes[k] = len(devices) // known
    names = tuple(sizes)
    shape = tuple(sizes[n] for n in names)
    total = int(np.prod(shape))
    if any(s < 1 for s in shape) or len(devices) < total:
        raise ValueError(
            "make_mesh: axes %r need %d devices but only %d are available "
            "(run under an n-device backend, e.g. XLA_FLAGS="
            "--xla_force_host_platform_device_count=%d with JAX_PLATFORMS=cpu)"
            % (dict(zip(names, shape)), total, len(devices), total))
    arr = np.asarray(devices[:total]).reshape(shape)
    return Mesh(arr, names)


def data_parallel_mesh(num_devices=None, devices=None):
    devices = devices if devices is not None else jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return make_mesh({"dp": len(devices)}, devices)


def replicated(mesh):
    return NamedSharding(mesh, P())


def batch_sharded(mesh, ndim, axis_name="dp", batch_dim=0):
    spec = [None] * ndim
    spec[batch_dim] = axis_name
    return NamedSharding(mesh, P(*spec))
