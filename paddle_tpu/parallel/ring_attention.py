"""Ring attention: exact attention over sequences sharded across chips.

TPU-first long-context support (SURVEY.md §2 "long-context"). The reference
(mozga-intel/Paddle, March 2018) has no attention-parallelism at all — its
ring is the pserver update ring (python/paddle/v2/master, pserver/). Here the
ring is over the `sp` mesh axis: Q/K/V live sharded on the sequence dim, each
chip holds one block, and K/V blocks rotate around the ring via ppermute over
ICI while every chip accumulates its Q-block's attention with an online
(flash-style, numerically stable) softmax. Peak memory per chip is O(T/sp · T/sp)
instead of O(T·T), and no chip ever materializes the full sequence.

Layout convention: [batch, seq, heads, head_dim] ("BTHD"), sharded P(dp, sp)
on (batch, seq). Works under jit inside a Mesh context; differentiable
(jax.grad flows through shard_map + ppermute, giving the ring backward pass
with reverse-direction permutes automatically).
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from .mesh import P, vary as _vary

__all__ = ["ring_attention", "attention_reference", "ring_attention_sharded",
           "sequence_parallel_specs"]

_NEG_INF = -1e30


def attention_reference(q, k, v, causal=False, scale=None, kv_len=None):
    """Dense single-device attention, [B,T,H,D]. The numerical reference the
    ring path must match; also the fallback when no `sp` axis exists.
    kv_len: optional [B] true key lengths (key-padding mask)."""
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), dtype=bool))
        logits = jnp.where(mask, logits, _NEG_INF)
    if kv_len is not None:
        # accept [B] or the fluid-convention [B, 1] (the flash kernel
        # normalizes the same way; a [B, 1] here would silently
        # broadcast the mask to rank 5)
        kv_len = jnp.asarray(kv_len).reshape(k.shape[0])
        kpos = jnp.arange(k.shape[1])
        kmask = kpos[None, :] < kv_len[:, None]           # [B, Tk]
        logits = jnp.where(kmask[:, None, None, :], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _block_attend(q, k, v, m, l, o, q_off, k_off, causal, scale,
                  kv_len=None):
    """One online-softmax accumulation step against a single K/V block.

    q: [B,Tq,H,D]  k,v: [B,Tk,H,D]  m,l: [B,H,Tq]  o: [B,Tq,H,D]
    q_off/k_off: global position offsets of the blocks (for causal mask
    and the kv_len key-padding mask; kv_len is [B] true key lengths).
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale  # [B,H,Tq,Tk]
    kpos = k_off + jnp.arange(k.shape[1])
    if causal:
        qpos = q_off + jnp.arange(q.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    if kv_len is not None:
        kmask = kpos[None, :] < kv_len[:, None]           # [B, Tk]
        logits = jnp.where(kmask[:, None, None, :], logits, _NEG_INF)
    m_blk = jnp.max(logits, axis=-1)                      # [B,H,Tq]
    m_new = jnp.maximum(m, m_blk)
    p = jnp.exp(logits - m_new[..., None])                # [B,H,Tq,Tk]
    if causal or kv_len is not None:
        # fully-masked rows would give exp(NEG_INF - NEG_INF) = 1 everywhere;
        # force masked entries to exact zero so l stays 0 and the final
        # clamp yields a zero output row
        p = jnp.where(logits <= _NEG_INF * 0.5, 0.0, p)
    corr = jnp.exp(m - m_new)                             # [B,H,Tq]
    l_new = l * corr + jnp.sum(p, axis=-1)
    # o is [B,Tq,H,D]; corr broadcasts as [B,Tq,H,1]
    corr_o = jnp.transpose(corr, (0, 2, 1))[..., None]
    o_new = o * corr_o + jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return m_new, l_new, o_new


def _ring_body(axis_name, n, causal, scale, t_q, t_k, kv_len=None):
    def body(step, carry):
        k, v, m, l, o, q, my_idx = carry
        # block currently held arrived from device (my_idx - step) mod n
        src = jnp.mod(my_idx - step, n)
        m, l, o = _block_attend(q, k, v, m, l, o,
                                q_off=my_idx * t_q, k_off=src * t_k,
                                causal=causal, scale=scale, kv_len=kv_len)
        # rotate K/V one hop around the ring (skip after the last block so
        # the loop does exactly n-1 permutes)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k, v = lax.cond(
            step < n - 1,
            lambda kv: tuple(lax.ppermute(x, axis_name, perm) for x in kv),
            lambda kv: kv, (k, v))
        return (k, v, m, l, o, q, my_idx)
    return body


def ring_attention(q, k, v, axis_name="sp", causal=False, scale=None,
                   vary_axes=None, kv_len=None):
    """Per-shard ring attention; call inside shard_map over `axis_name`.

    q,k,v: the LOCAL sequence blocks [B, T/sp, H, D]. kv_len: optional
    [B] int32 GLOBAL true key lengths (padded-batch masking — keys at
    global position >= kv_len contribute nothing; same contract as
    pallas flash_attention's kv_len). Returns local output block
    [B, T/sp, H, D]. Exact (not approximate): matches
    attention_reference on the gathered result to fp32 tolerance.
    """
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    # accumulators start as constants; mark them device-varying over the ring
    # axis so the fori_loop carry type is stable under shard_map
    axes = tuple(vary_axes or (axis_name,))
    m0 = _vary(jnp.full((b, h, t_q), _NEG_INF, dtype=jnp.float32), axes)
    l0 = _vary(jnp.zeros((b, h, t_q), dtype=jnp.float32), axes)
    o0 = _vary(jnp.zeros(q.shape, dtype=jnp.float32), axes)
    if kv_len is not None:
        kv_len = jnp.asarray(kv_len, jnp.int32).reshape(b)
    body = _ring_body(axis_name, n, causal, scale, t_q, t_k, kv_len=kv_len)
    _, _, m, l, o, _, _ = lax.fori_loop(
        0, n, body, (k, v, m0, l0, o0, q.astype(jnp.float32), my_idx))
    l = jnp.maximum(l, 1e-30)  # fully-masked rows (strict causal pad) → 0 out
    out = o / jnp.transpose(l, (0, 2, 1))[..., None]
    return out.astype(q.dtype)


def sequence_parallel_specs(batch_axis="dp", seq_axis="sp"):
    """PartitionSpecs for BTHD activations under sequence parallelism."""
    return P(batch_axis, seq_axis, None, None)


def sp_spec_for_mesh(mesh, batch_axis, seq_axis):
    """The [B,T,H,D] PartitionSpec for an SP entry point on `mesh`: batch
    over batch_axis when the mesh has one, sequence over seq_axis. Shared
    by ring_attention_sharded and ulysses_attention_sharded."""
    if batch_axis in mesh.axis_names:
        return sequence_parallel_specs(batch_axis, seq_axis), \
            (batch_axis, seq_axis)
    return P(None, seq_axis, None, None), (seq_axis,)


def sp_shard_call(body, q, k, v, mesh, batch_axis, seq_axis, kv_len):
    """Shared SP entry plumbing for ring and ulysses: shard q/k/v over
    (batch_axis, seq_axis), kv_len (if any) over the batch axis, and run
    `body(qs, ks, vs, lens)` per shard. The single place that owns the
    kv_len sharding contract ([B] int32, batch-sharded)."""
    spec, _ = sp_spec_for_mesh(mesh, batch_axis, seq_axis)
    if kv_len is None:
        fn = shard_map(lambda qs, ks, vs: body(qs, ks, vs, None),
                       mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
        return fn(q, k, v)
    len_spec = P(batch_axis) if batch_axis in mesh.axis_names else P()
    fn = shard_map(body, mesh=mesh,
                   in_specs=(spec, spec, spec, len_spec), out_specs=spec)
    return fn(q, k, v, jnp.asarray(kv_len, jnp.int32).reshape(q.shape[0]))


def ring_attention_sharded(q, k, v, mesh, causal=False, scale=None,
                           batch_axis="dp", seq_axis="sp", kv_len=None):
    """Global-view ring attention: q,k,v are full [B,T,H,D] arrays (or GSPMD
    -sharded); shard_map splits them over (dp, sp) and runs the ring.
    kv_len: optional [B] int32 global true key lengths (sharded over the
    batch axis like q's batch dim).
    """
    _, vary_axes = sp_spec_for_mesh(mesh, batch_axis, seq_axis)

    def body(qs, ks, vs, lens):
        return ring_attention(qs, ks, vs, axis_name=seq_axis, causal=causal,
                              scale=scale, vary_axes=vary_axes, kv_len=lens)

    return sp_shard_call(body, q, k, v, mesh, batch_axis, seq_axis, kv_len)
