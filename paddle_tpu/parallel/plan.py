"""ShardingPlan: data-parallel distribution as a first-class compile-time
object (ARCHITECTURE.md §21).

The reference's distribution story is imperative — NCCL allreduce op
handles inserted into the SSA graph, every chip holding every param and
every optimizer moment. The TPU-native story is declarative: ONE plan
object assigns every param, gradient and optimizer accumulator a
`NamedSharding`/`PartitionSpec` over the mesh, and the whole-program jit
(pjit) lowers it — XLA GSPMD turns the gradient all-reduce into a
reduce-scatter onto the owning shard, runs the update ops on the 1/N
shard of params + moments, and all-gathers params on use. That is the
ZeRO-style weight-update sharding of Xu et al. 2020 (arXiv:2004.13336),
expressed as data instead of as executor behavior.

Why a first-class object instead of the executor's internal dict:

  * deterministic + restart-stable — the partitioner walks params in
    sorted-name order and every decision is a pure function of
    (name, shape, mesh), so two processes building the same program get
    byte-identical plans (the compile-cache key depends on it);
  * inspectable — every decision carries its reason ("dim0 13 %% 8 != 0
    -> replicated"), `memory_report()` prices the per-chip update-state
    bytes the plan buys, and `describe()` renders the table;
  * serializable — `to_json()`/`digest()` join the persistent AOT
    compile-cache key (a changed plan is a different executable) and
    ride checkpoint metadata, and `CheckpointManager.restore(layout=
    plan)` re-splits a snapshot straight onto the plan's layout.

The partitioner rule (deliberately boring, so it is predictable):
shard dim 0 of a value over `shard_axis` when the axis size divides it
evenly (and the value is at least axis-size elements); otherwise
replicate, with the reason logged. Optimizer accumulators follow their
owner param (exact `program._accumulator_owner` map first, longest-name
pattern fallback for metadata-less deserialized programs). Per-var
overrides — explicit `param_shardings` or `ParamAttr(mesh_axes=...)`
annotations — always win over the automatic assignment.

Tensor parallelism (ARCHITECTURE.md §23) is the same plan generalized
from dim-0 weight-update sharding to intra-layer PartitionSpecs over a
2D tp×dp mesh: `build(..., tp_axis="tp")` arms a per-family auto rule
driven by each param's CONSUMER ops (the known op set):

  matmul family (mul/matmul "Y", the fc weight):
      [in, out] — column-parallel P(None, tp) when tp divides `out`,
      else row-parallel P(tp, None) when tp divides `in`
  embedding (lookup_table "W"):
      [vocab, emb] — vocab-parallel P(tp, None), else P(None, tp)
  conv family (conv2d / depthwise / transpose "Filter"):
      [out_c, in_c, kh, kw] — output-channel-parallel P(tp, None, ...)

Anything the rule can't place (biases, norms, non-dividing dims, ops
outside the set) replicates over tp with the reason logged;
`ParamAttr(mesh_axes=)` annotations and explicit overrides still win.
Gradients mirror their param's spec and optimizer accumulators follow
their owner, exactly as in the ZeRO case, so the SAME
`grad_constraints()` seam pins the backward's collectives and GSPMD
places the all-gather/reduce-scatter where the spec demands
(arXiv:2004.13336's gather/scatter placement, generalized). The auto
TP rule composes with `shard_update=True`: a param the TP rule placed
keeps its intra-layer spec; the ZeRO dim-0 rule picks up the rest.

Two placements per TP param, selected by `tp_placement`:

  "gather" (default) — params AND their accumulators live SHARDED at
      rest (1/tp of each per chip: the bigger-than-one-chip memory
      claim) and `param_gather_constraints()` pins their traced values
      replicated at the moment they enter the step, so GSPMD
      materializes explicit all-gathers on use, every contraction AND
      the optimizer update run on full arrays, and the math is
      BIT-IDENTICAL to the replicated baseline; grads and updated
      state land back on the shards at the executor's out_shardings
      boundary (reduce-scatter). The at-REST footprint is 1/tp; the
      in-STEP peak is shards + the gathered arrays XLA keeps live,
      the classic weight-gather tradeoff of arXiv:2004.13336.
  "compute" — no gather constraint: GSPMD partitions the contractions
      themselves (Megatron-style partial products + all-reduce).
      Cheaper activation traffic on wide layers, but the split
      reduction tree rounds differently at the ulp level — a perf
      mode for hardware sweeps, not a bit-exactness mode.
"""
import hashlib
import json
import logging

import numpy as np

from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["ShardingPlan", "VarPlan", "PLAN_FORMAT_VERSION"]

log = logging.getLogger("paddle_tpu.parallel.plan")

# v2: intra-layer tensor-parallel specs (tp_axis in the JSON form, 2D
# specs from the per-family auto rule) — a changed format version is a
# changed digest, so v1-keyed AOT artifacts are not served to v2 plans
PLAN_FORMAT_VERSION = 2

TP_PLACEMENTS = ("gather", "compute")

# entry kinds
PARAM = "param"
ACCUMULATOR = "accumulator"
OPTIMIZER_GLOBAL = "optimizer_global"
GRADIENT = "gradient"


def _match_accumulator_param(vname, params_by_len_desc):
    """Fallback accumulator->param attribution by the naming convention
    "<acc>_<param>_<n>" when program._accumulator_owner has no entry.
    params_by_len_desc must be sorted longest-first so `fc.w` never claims
    `my_fc.w`'s accumulator."""
    import re
    return next(
        (p for p in params_by_len_desc
         if re.search(r"(^|_)%s(_\d+)?$" % re.escape(p), vname)),
        None)


# The known op set the auto tensor-parallel rule covers, in precedence
# order (a param consumed by several families takes the first match):
# (family, {(op_type, input slot), ...}) — the slot is where the WEIGHT
# rides, so an activation feeding a matmul's "X" never matches.
_TP_FAMILIES = (
    ("matmul", frozenset({("mul", "Y"), ("matmul", "Y")})),
    ("embedding", frozenset({("lookup_table", "W")})),
    ("conv", frozenset({("conv2d", "Filter"),
                        ("depthwise_conv2d", "Filter"),
                        ("conv2d_transpose", "Filter")})),
)


def _param_consumers(program):
    """{var name: set of (op_type, input_slot)} over every forward op of
    every block. grad_of ops are skipped: they replay the forward's
    inputs, and double-counting them could not change a family match."""
    cons = {}
    for block in program.blocks:
        for op in block.ops:
            if op.type == "grad_of":
                continue
            for slot, names in op.inputs.items():
                for n in names:
                    if n:
                        cons.setdefault(n, set()).add((op.type, slot))
    return cons


def _auto_tp_spec(name, shape, consumers, tp_axis, n_tp):
    """The per-family tensor-parallel assignment for one param, or
    (None, reason) when no family rule places it (caller falls through
    to the ZeRO rule / replicated). Pure function of
    (name, shape, consumer set, axis, size) — deterministic, so the
    plan digest is restart-stable like the rest of the partitioner."""
    shape = tuple(shape or ())
    uses = consumers.get(name, ())
    family = next((fam for fam, sigs in _TP_FAMILIES
                   if any(u in sigs for u in uses)), None)
    if family is None:
        return None, "no tensor-parallel family consumes it"
    if any(d is None or d < 0 for d in shape):
        return None, "%s family but no concrete shape" % family

    def divides(d):
        return d % n_tp == 0

    if family == "matmul" and len(shape) == 2:
        if divides(shape[1]):
            return P(None, tp_axis), ("column-parallel: matmul out dim "
                                      "%d / %d over %r"
                                      % (shape[1], n_tp, tp_axis))
        if divides(shape[0]):
            return P(tp_axis, None), ("row-parallel: matmul in dim "
                                      "%d / %d over %r"
                                      % (shape[0], n_tp, tp_axis))
        return None, ("matmul dims %r: %d divides neither -> replicated"
                      % (shape, n_tp))
    if family == "embedding" and len(shape) == 2:
        if divides(shape[0]):
            return P(tp_axis, None), ("vocab-parallel: embedding dim0 "
                                      "%d / %d over %r"
                                      % (shape[0], n_tp, tp_axis))
        if divides(shape[1]):
            return P(None, tp_axis), ("embedding-dim-parallel: dim1 "
                                      "%d / %d over %r"
                                      % (shape[1], n_tp, tp_axis))
        return None, ("embedding dims %r: %d divides neither -> "
                      "replicated" % (shape, n_tp))
    if family == "conv" and len(shape) == 4:
        if divides(shape[0]):
            return P(tp_axis, None, None, None), (
                "output-channel-parallel: conv out_c %d / %d over %r"
                % (shape[0], n_tp, tp_axis))
        return None, ("conv out_c %d %% %d != 0 -> replicated"
                      % (shape[0], n_tp))
    return None, ("%s family but unexpected rank %d -> replicated"
                  % (family, len(shape)))


def _spec_to_json(spec):
    """PartitionSpec -> JSON list (str | [str, ...] | None per dim)."""
    out = []
    for p in tuple(spec):
        if isinstance(p, (list, tuple)):
            out.append([str(a) for a in p])
        else:
            out.append(None if p is None else str(p))
    return out


def _spec_from_json(spec):
    """Inverse of _spec_to_json: JSON list -> plain per-dim tuple of axis
    names / axis tuples / None. Deliberately NOT a PartitionSpec — this
    feeds analysis.PlanView, which must work on machines that cannot
    build the plan's mesh (linting an 8-chip plan on a 1-CPU box)."""
    out = []
    for p in spec:
        if isinstance(p, (list, tuple)):
            out.append(tuple(str(a) for a in p))
        else:
            out.append(None if p is None else str(p))
    return tuple(out)


def _spec_shard_factor(spec, mesh):
    """How many ways `spec` splits a value over `mesh` (the per-chip
    memory divisor): product of the sizes of every mesh axis the spec
    uses."""
    factor = 1
    for ent in tuple(spec):
        axes = ent if isinstance(ent, (list, tuple)) else (
            () if ent is None else (ent,))
        for a in axes:
            factor *= int(mesh.shape.get(a, 1))
    return factor


def _dtype_bytes(dtype):
    try:
        from ..core.framework import convert_dtype
        return int(np.dtype(convert_dtype(dtype)).itemsize)
    except Exception:  # noqa: BLE001 — unknown dtype prices as f32
        return 4


class VarPlan(object):
    """One variable's assignment: its PartitionSpec over the mesh, what
    kind of state it is, which param owns it (accumulators), whether the
    caller pinned it (override), and WHY the partitioner chose this
    spec."""

    __slots__ = ("name", "spec", "kind", "owner", "override", "reason",
                 "shape", "dtype")

    def __init__(self, name, spec, kind, owner=None, override=False,
                 reason="", shape=None, dtype=None):
        self.name = name
        self.spec = spec
        self.kind = kind
        self.owner = owner
        self.override = bool(override)
        self.reason = reason
        self.shape = None if shape is None else tuple(shape)
        self.dtype = dtype

    @property
    def sharded(self):
        return any(p is not None for p in tuple(self.spec))

    def to_json(self):
        d = {"spec": _spec_to_json(self.spec), "kind": self.kind}
        if self.owner is not None:
            d["owner"] = self.owner
        if self.override:
            d["override"] = True
        if self.reason:
            d["reason"] = self.reason
        return d

    def __repr__(self):
        return "VarPlan(%r, %r, %s%s)" % (
            self.name, tuple(self.spec), self.kind,
            ", override" if self.override else "")


class ShardingPlan(object):
    """The explicit compile-time distribution plan one ParallelExecutor
    dispatch runs under. Build with `ShardingPlan.build(program, mesh)`
    (the deterministic partitioner) or construct directly from entries.

    `batch_axis` shards activations (feeds split on their batch dim);
    `shard_axis` shards the weight update — params, grads and optimizer
    accumulators split dim 0 over it (ZeRO-style). They default to the
    same mesh axis ('dp'): reduce-scatter lands each gradient shard on
    the replica that owns the matching param shard."""

    def __init__(self, mesh, entries=(), batch_axis="dp", shard_axis=None,
                 tp_axis=None, tp_placement="gather"):
        self.mesh = mesh
        self.batch_axis = batch_axis
        # an EXPLICIT shard_axis/tp_axis must name a real mesh axis — a
        # typo would silently partition nothing (size-1 default) and the
        # user would discover the full replicated footprint at OOM. The
        # batch-axis fallback stays lenient: a mesh without the batch
        # axis legitimately means "no update sharding here" (size 1).
        if shard_axis is not None and shard_axis not in mesh.axis_names:
            raise ValueError(
                "shard_axis %r is not an axis of mesh %r"
                % (shard_axis, dict(mesh.shape)))
        if tp_axis is not None and tp_axis not in mesh.axis_names:
            raise ValueError(
                "tp_axis %r is not an axis of mesh %r"
                % (tp_axis, dict(mesh.shape)))
        if tp_placement not in TP_PLACEMENTS:
            raise ValueError("tp_placement must be one of %r, got %r"
                             % (TP_PLACEMENTS, tp_placement))
        self.shard_axis = shard_axis if shard_axis is not None \
            else batch_axis
        self.tp_axis = tp_axis
        self.tp_placement = tp_placement
        self.entries = {}
        for e in entries:
            self.entries[e.name] = e

    # ------------------------------------------------------------ build --
    @classmethod
    def build(cls, program, mesh, batch_axis="dp", shard_axis=None,
              shard_update=False, overrides=None, tp_axis=None,
              tp_placement="gather"):
        """Deterministic partitioner over `program`'s persistable state.

        Precedence per var: explicit `overrides` (any var name ->
        PartitionSpec — the executor's `param_shardings` arg) >
        `ParamAttr(mesh_axes=...)` annotations (accumulators follow their
        annotated owner) > the automatic tensor-parallel per-family rule
        (only with `tp_axis=` set — see _auto_tp_spec) > the automatic
        ZeRO assignment (only with `shard_update=True`) > replicated.
        Params are walked in sorted-name order and every decision
        depends only on (name, shape, consumer ops, mesh axes), so the
        plan — and with it the compile-cache key — is identical across
        process restarts (see the canonical-order contract in
        optimizer.py / core/backward.py for why the program bytes are
        too).

        A param no rule can split evenly falls back to replicated with
        a logged reason — never an error: the plan must accept any
        program, partial sharding is still a win.
        """
        if shard_axis is not None and shard_axis not in mesh.axis_names:
            # same guard as __init__: an explicit axis must exist
            raise ValueError(
                "shard_axis %r is not an axis of mesh %r"
                % (shard_axis, dict(mesh.shape)))
        if tp_axis is not None and tp_axis not in mesh.axis_names:
            raise ValueError(
                "tp_axis %r is not an axis of mesh %r"
                % (tp_axis, dict(mesh.shape)))
        shard_axis = shard_axis if shard_axis is not None else batch_axis
        overrides = dict(overrides or {})
        n_shard = int(mesh.shape.get(shard_axis, 1))
        n_tp = int(mesh.shape.get(tp_axis, 1)) if tp_axis else 1
        consumers = _param_consumers(program) if tp_axis else {}
        entries = []
        taken = set()

        params = {p.name: p for p in
                  program.global_block().all_parameters()}

        def _annotation_spec(p):
            axes = getattr(p, "mesh_axes", None)
            if not axes:
                return None
            resolved = [a if a in mesh.axis_names else None for a in axes]
            if all(a is None for a in resolved):
                # annotation names no axis of THIS mesh: a no-op, the
                # same model definition reused on a dp-only mesh keeps
                # its ZeRO sharding instead of degrading to replication
                return None
            return P(*resolved)

        def _auto_spec(name, shape):
            tp_reason = ""
            if tp_axis is not None and n_tp > 1:
                spec, tp_reason = _auto_tp_spec(name, shape, consumers,
                                                tp_axis, n_tp)
                if spec is not None:
                    return spec, tp_reason
                log.info("sharding plan: %s not tensor-parallel: %s",
                         name, tp_reason)
                # fall through: the ZeRO dim-0 rule (below) may still
                # shard the update of a param the TP rule passed on
            elif tp_axis is not None and not shard_update:
                return P(), "mesh axis %r has size 1" % tp_axis
            if not shard_update:
                return P(), tp_reason
            if n_shard <= 1:
                return P(), "mesh axis %r has size 1" % shard_axis
            shape = tuple(shape or ())
            if not shape or shape[0] is None:
                return P(), "no concrete leading dim"
            if shape[0] % n_shard != 0:
                reason = ("dim0 %d %% %d (%r) != 0 -> replicated"
                          % (shape[0], n_shard, shard_axis))
                log.info("sharding plan: %s stays replicated: %s",
                         name, reason)
                return P(), reason
            if int(np.prod(shape)) < n_shard:
                reason = ("%d elements < %d-way %r axis -> replicated"
                          % (int(np.prod(shape)), n_shard, shard_axis))
                log.info("sharding plan: %s stays replicated: %s",
                         name, reason)
                return P(), reason
            return P(shard_axis), "dim0 %d / %d over %r" % (
                shape[0], n_shard, shard_axis)

        # params, sorted-name order (restart-stable walk)
        follow = {}   # param -> spec its accumulators follow
        for name in sorted(params):
            p = params[name]
            taken.add(name)
            if name in overrides:
                spec = overrides[name]
                entries.append(VarPlan(name, spec, PARAM, override=True,
                                       reason="explicit override",
                                       shape=p.shape, dtype=p.dtype))
                # explicit overrides do NOT cascade to accumulators (the
                # caller pinned exactly one var); annotations do
                continue
            ann = _annotation_spec(p)
            if ann is not None:
                entries.append(VarPlan(name, ann, PARAM,
                                       reason="ParamAttr mesh_axes",
                                       shape=p.shape, dtype=p.dtype))
                follow[name] = ann
                continue
            spec, reason = _auto_spec(name, p.shape)
            entries.append(VarPlan(name, spec, PARAM, reason=reason,
                                   shape=p.shape, dtype=p.dtype))
            if spec != P():
                follow[name] = spec

        # optimizer accumulators follow their owner param. Resolution
        # goes through the exact program._accumulator_owner map; the
        # name-pattern fallback (longest param name wins) only covers
        # programs deserialized without optimizer metadata.
        acc_owner = getattr(program, "_accumulator_owner", {})
        by_len = sorted(params, key=len, reverse=True)
        for vname in sorted(program.global_block().vars):
            v = program.global_block().vars[vname]
            if vname in taken or not getattr(v, "persistable", False):
                continue
            owner = acc_owner.get(vname)
            if owner is None:
                owner = _match_accumulator_param(vname, by_len)
            if owner == "":
                # optimizer-global state (beta pows, counters): [1]
                # scalars — nothing to shard, and the "" owner mark
                # guarantees no param can claim them
                if vname in overrides:
                    entries.append(VarPlan(
                        vname, overrides[vname], OPTIMIZER_GLOBAL,
                        owner="", override=True,
                        reason="explicit override",
                        shape=v.shape, dtype=v.dtype))
                else:
                    entries.append(VarPlan(
                        vname, P(), OPTIMIZER_GLOBAL, owner="",
                        reason="optimizer-global scalar",
                        shape=v.shape, dtype=v.dtype))
                continue
            if owner is None or owner not in params:
                continue  # not optimizer state — plain persistable
            if vname in overrides:
                entries.append(VarPlan(
                    vname, overrides[vname], ACCUMULATOR, owner=owner,
                    override=True, reason="explicit override",
                    shape=v.shape, dtype=v.dtype))
                continue
            ospec = follow.get(owner)
            same_shape = tuple(v.shape or ()) == tuple(
                params[owner].shape or ())
            if ospec is not None and same_shape:
                entries.append(VarPlan(
                    vname, ospec, ACCUMULATOR, owner=owner,
                    reason="follows owner %r" % owner,
                    shape=v.shape, dtype=v.dtype))
            else:
                reason = ("owner %r replicated" % owner
                          if ospec is None else
                          "shape differs from owner %r -> replicated"
                          % owner)
                entries.append(VarPlan(
                    vname, P(), ACCUMULATOR, owner=owner, reason=reason,
                    shape=v.shape, dtype=v.dtype))

        # any override naming a var the walk didn't classify (fetch-only
        # persistables, caller-known state) still lands in the plan
        for vname in sorted(set(overrides) -
                            {e.name for e in entries}):
            from ..core.utils import find_var
            v = find_var(program, vname)
            entries.append(VarPlan(
                vname, overrides[vname], PARAM, override=True,
                reason="explicit override",
                shape=getattr(v, "shape", None),
                dtype=getattr(v, "dtype", None)))

        # gradients mirror their param's spec: the reduce-scatter target.
        # Only sharded params get one — a replicated param's grad is the
        # plain all-reduce GSPMD already inserts.
        from ..core.framework import GRAD_SUFFIX
        for e in [e for e in entries if e.kind == PARAM and e.sharded]:
            entries.append(VarPlan(
                e.name + GRAD_SUFFIX, e.spec, GRADIENT, owner=e.name,
                reason="reduce-scatter onto owner's shard",
                shape=e.shape, dtype=e.dtype))

        return cls(mesh, entries, batch_axis=batch_axis,
                   shard_axis=shard_axis, tp_axis=tp_axis,
                   tp_placement=tp_placement)

    # ----------------------------------------------------------- query --
    def spec_for(self, name):
        """The PartitionSpec assigned to `name`, or None when the plan
        has no entry for it (callers treat that as replicated)."""
        e = self.entries.get(name)
        return None if e is None else e.spec

    def sharding_for(self, name):
        """NamedSharding for `name` (replicated when unplanned) — what
        the executor device_puts state with and what
        CheckpointManager.restore(layout=plan) re-splits onto."""
        spec = self.spec_for(name)
        return NamedSharding(self.mesh, spec if spec is not None else P())

    def spec_map(self):
        """{name: PartitionSpec} for every non-gradient entry that is
        sharded or explicitly overridden — the executor's
        `_param_shardings` view (replicated auto entries are implied)."""
        return {e.name: e.spec for e in self.entries.values()
                if e.kind != GRADIENT and (e.sharded or e.override)}

    def grad_constraints(self):
        """{grad_name: NamedSharding} the lowering pins with
        `with_sharding_constraint`: each sharded param's gradient is
        constrained to the owner's shard layout, so GSPMD lowers the
        cross-replica gradient sum as reduce-scatter (each replica
        receives only the 1/N slice its update needs) instead of a full
        all-reduce followed by a slice.

        Gather-placed tensor-parallel params are EXEMPT: their step
        computes replicated end-to-end (that is the placement's
        bit-exactness contract — an in-graph sharded grad re-tiles the
        backward dots and drifts at the ulp level on some backends);
        their grads land on the shard at the executor's sharded
        out_shardings boundary instead, where GSPMD still lowers the
        dp-sum + scatter as one reduce-scatter."""
        skip = frozenset(self.param_gather_constraints())
        return {e.name: NamedSharding(self.mesh, e.spec)
                for e in self.entries.values()
                if e.kind == GRADIENT and e.owner not in skip}

    def _spec_uses_tp(self, spec):
        for ent in tuple(spec):
            axes = ent if isinstance(ent, (list, tuple)) else (
                () if ent is None else (ent,))
            if self.tp_axis in axes:
                return True
        return False

    def param_gather_constraints(self):
        """{param name: replicated NamedSharding} for every
        tensor-parallel param under `tp_placement="gather"` — the gather
        half of the placement (arXiv:2004.13336): the executor pins each
        such param's traced value replicated at the step's entry
        (Env.write, the same seam grad_constraints rides), so the param
        lives 1/tp-sharded AT REST in the scope/in_shardings but every
        contraction consumes the full gathered weight. Compute is then
        bit-identical to the replicated baseline; the gradient's
        reduce-scatter constraint (above) and the sharded out_shardings
        land the update back on the shard. Empty for
        tp_placement="compute" (GSPMD partitions the contractions) and
        for plans with no tp axis — the ZeRO dim-0 case keeps PR-9
        behavior, where GSPMD already gathers on use by itself."""
        if not self.tp_axis or self.tp_placement != "gather":
            return {}
        rep = NamedSharding(self.mesh, P())
        # accumulators riding a TP owner gather too: a moment sharded
        # at rest but updated replicated keeps the whole optimizer step
        # on full arrays — a partitioned elementwise update vectorizes
        # (FMA-fuses) differently on some backends, which is exactly
        # the ulp drift the gather placement exists to exclude
        return {e.name: rep for e in self.entries.values()
                if e.kind in (PARAM, ACCUMULATOR)
                and self._spec_uses_tp(e.spec)}

    def __len__(self):
        return len(self.entries)

    def __iter__(self):
        return iter(sorted(self.entries.values(), key=lambda e: e.name))

    # ------------------------------------------------------- serialize --
    def to_json(self):
        """Canonical JSON form: joins the persistent AOT compile-cache
        key (any plan change re-keys the serialized executable) and
        checkpoint metadata. Deterministic: vars sorted, mesh axes in
        mesh order."""
        return {
            "version": PLAN_FORMAT_VERSION,
            "mesh_axes": [[a, int(s)] for a, s in self.mesh.shape.items()],
            "batch_axis": self.batch_axis,
            "shard_axis": self.shard_axis,
            "tp_axis": self.tp_axis,
            "tp_placement": self.tp_placement,
            "vars": {n: self.entries[n].to_json()
                     for n in sorted(self.entries)},
        }

    def digest(self):
        blob = json.dumps(self.to_json(), sort_keys=True).encode("utf-8")
        return hashlib.sha256(blob).hexdigest()

    # ------------------------------------------------------ accounting --
    def memory_report(self):
        """Per-chip memory accounting for the state the plan places —
        the number the ZeRO sharding exists to move. For each entry:
        global bytes (shape x dtype) and per-chip bytes (global /
        shard factor). `update_state` covers optimizer accumulators +
        optimizer-global scalars — the footprint the replicated
        reference design pays N times over; `params` is priced the same
        way (sharded-at-rest params all-gather on use). Gradient
        entries are transient (not resident state) and excluded."""
        n = int(self.mesh.devices.size)
        rep = {"params": {"global_bytes": 0, "per_chip_bytes": 0,
                          "replicated_per_chip_bytes": 0},
               "update_state": {"global_bytes": 0, "per_chip_bytes": 0,
                                "replicated_per_chip_bytes": 0}}
        sharded_vars, replicated_vars = [], []
        for e in self.entries.values():
            if e.kind == GRADIENT or e.shape is None:
                continue
            shape = [d for d in e.shape if d is not None and d >= 0]
            nbytes = int(np.prod(shape or [1])) * _dtype_bytes(e.dtype)
            bucket = rep["params" if e.kind == PARAM else "update_state"]
            factor = _spec_shard_factor(e.spec, self.mesh)
            bucket["global_bytes"] += nbytes
            bucket["per_chip_bytes"] += nbytes // factor
            bucket["replicated_per_chip_bytes"] += nbytes
            (sharded_vars if factor > 1 else replicated_vars).append(
                e.name)
        return {"num_devices": n,
                "shard_axis": self.shard_axis,
                "shard_axis_size": int(self.mesh.shape.get(
                    self.shard_axis, 1)),
                "tp_axis": self.tp_axis,
                "tp_axis_size": int(self.mesh.shape.get(
                    self.tp_axis, 1)) if self.tp_axis else 1,
                "params": rep["params"],
                "update_state": rep["update_state"],
                "sharded_vars": sorted(sharded_vars),
                "replicated_vars": sorted(replicated_vars)}

    def describe(self):
        """Human-readable plan table (one line per var + the memory
        footer) — what `print(pexe.plan.describe())` shows."""
        lines = ["ShardingPlan over %s (batch=%r, shard=%r%s)"
                 % (dict(self.mesh.shape), self.batch_axis,
                    self.shard_axis,
                    ", tp=%r" % self.tp_axis if self.tp_axis else "")]
        for e in self:
            lines.append("  %-40s %-12s %-18s %s" % (
                e.name, e.kind, str(tuple(e.spec)),
                e.reason + (" [override]" if e.override else "")))
        m = self.memory_report()
        lines.append(
            "  update state/chip: %d B (replicated would be %d B)"
            % (m["update_state"]["per_chip_bytes"],
               m["update_state"]["replicated_per_chip_bytes"]))
        return "\n".join(lines)

    def __repr__(self):
        n_sharded = sum(1 for e in self.entries.values()
                        if e.kind != GRADIENT and e.sharded)
        return ("ShardingPlan(mesh=%s, %d vars, %d sharded, shard_axis=%r)"
                % (dict(self.mesh.shape),
                   sum(1 for e in self.entries.values()
                       if e.kind != GRADIENT),
                   n_sharded, self.shard_axis))
