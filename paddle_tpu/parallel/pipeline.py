"""Pipeline parallelism (`pp` mesh axis): GPipe-style looped pipeline.

TPU-first addition (SURVEY.md §2 "DP/TP/PP/SP composable on one Mesh"). The
reference (mozga-intel/Paddle, March 2018) predates pipeline parallelism —
its only model-partitioning story is the pserver split
(python/paddle/fluid/distribute_transpiler.py), which shards *parameters*,
not *stages*. Here stages are real: layer s of a homogeneous stack lives on
pipeline rank s, microbatches stream through the ring, and activations hop
stage→stage over ICI via `lax.ppermute` while every chip stays busy (after
the S-1-step fill bubble).

Design (the scaling-book looped-pipeline recipe):
- stage parameters are STACKED on a leading [S, ...] dim and sharded
  P('pp') — each chip holds exactly its stage's weights, no replication.
- the schedule is one `lax.scan` of length M + S - 1 (M microbatches):
  chip s computes microbatch t-s at step t; a single collective-permute per
  step shifts activations forward one stage. Bubble steps compute garbage
  that is `where`-masked out of the output buffer — static shapes, no
  data-dependent control flow, exactly what XLA wants.
- outputs accumulate on the last stage and are `psum`-broadcast over the
  ring at the end (zeros elsewhere), so the caller sees a replicated
  [B, ...] result it can feed a loss head.
- fully differentiable: the vjp of ppermute is the reverse permute, so
  jax.grad produces the backward pipeline (reverse schedule) automatically
  — no hand-written 1F1B machinery.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from .mesh import P, vary as _vary

__all__ = ["pipeline_apply", "pipeline_stages_spec", "stack_stage_params",
           "sequential_reference", "mlp_block_init", "mlp_block_apply",
           "mlp_block_specs"]


# ---------------------------------------------------------------------------
# The homogeneous pipeline STAGE block (absorbed from the seed-era
# parallel/tp.py — see MIGRATION.md). Program-level tensor parallelism
# is `ShardingPlan.build(..., tp_axis=)` (plan.py, ARCHITECTURE.md §23);
# these helpers survive only as the manual-mode stage math the pipeline
# schedule composes with: a Megatron-style column/row two-matmul block
# with one psum, runnable densely (tp_axis=None — the single-chip
# reference) or manually inside shard_map (a pipeline stage, where the
# 'pp' schedule is already manual and GSPMD can't place the collective).
# ---------------------------------------------------------------------------

def mlp_block_init(rng, d, d_hidden, scale=0.1):
    """Params for one tanh MLP block: [d -> d_hidden -> d] (shape-
    preserving, so it can serve as a homogeneous pipeline stage)."""
    k1, k2 = jax.random.split(jax.random.PRNGKey(rng)
                              if isinstance(rng, int) else rng)
    return {
        "w1": jax.random.normal(k1, (d, d_hidden), jnp.float32) * scale,
        "b1": jnp.zeros((d_hidden,), jnp.float32),
        "w2": jax.random.normal(k2, (d_hidden, d), jnp.float32) * scale,
        "b2": jnp.zeros((d,), jnp.float32),
    }


def mlp_block_specs(tp_axis="mp", pp_axis=None):
    """PartitionSpecs for (optionally stage-stacked) mlp_block params.

    Column-parallel w1/b1 split the hidden dim over ``tp_axis``; the
    row-parallel w2 splits its input (hidden) dim; b2 is replicated over
    mp (added after the psum). With ``pp_axis`` set, a leading stacked
    stage dim is sharded over it (pipeline composition — the
    `pipeline_apply(param_specs=...)` hook)."""
    def pp(*rest):
        return P(pp_axis, *rest) if pp_axis else P(*rest)
    return {
        "w1": pp(None, tp_axis),
        "b1": pp(tp_axis),
        "w2": pp(tp_axis, None),
        "b2": pp(None),
    }


def mlp_block_apply(params, x, tp_axis=None):
    """y = w2ᵀ·tanh(w1ᵀx + b1) + b2, with the hidden dim sharded over
    ``tp_axis`` when running manually inside shard_map (one psum — the
    Megatron pattern). With tp_axis=None this is the dense math (the
    single-chip reference, or a plain stage under the stacked 'pp'
    placement)."""
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    z = h @ params["w2"]
    if tp_axis is not None:
        z = lax.psum(z, tp_axis)
    return z + params["b2"]


def sequential_reference(stage_fn, stacked_params, x):
    """Single-device reference: apply the S stages in order."""
    S = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    out = x
    for s in range(S):
        p = jax.tree_util.tree_map(lambda a: a[s], stacked_params)
        out = stage_fn(p, out)
    return out


def stack_stage_params(per_stage_params):
    """[params_stage0, params_stage1, ...] -> one pytree with leading S dim
    (what pipeline_apply shards over 'pp')."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def pipeline_stages_spec(stacked_params, axis="pp"):
    """PartitionSpecs placing each stage's slice of the stacked params on its
    pipeline rank (leading dim sharded, everything else replicated)."""
    return jax.tree_util.tree_map(lambda _: P(axis), stacked_params)


def _pipeline_shard(params, xs, stage_fn, axis_name, vary_axes):
    """Per-shard body. params: stage-stacked pytree, locally [1, ...];
    xs: [M, mb, ...] microbatches (replicated over the pipeline axis).
    Returns [M, mb, ...] outputs, identical on every pipeline rank."""
    n = lax.psum(1, axis_name)
    s = lax.axis_index(axis_name)
    M = xs.shape[0]
    p_local = jax.tree_util.tree_map(lambda a: a[0], params)

    state0 = _vary(jnp.zeros(xs.shape[1:], xs.dtype), vary_axes)
    outs0 = _vary(jnp.zeros(xs.shape, xs.dtype), vary_axes)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        state, outs = carry
        mb = jnp.clip(t, 0, M - 1)
        # first stage consumes fresh microbatches; others the activation
        # ppermuted in from the previous stage last step
        inp = jnp.where(s == 0, xs[mb], state)
        y = stage_fn(p_local, inp)
        out_idx = t - (n - 1)
        oc = jnp.clip(out_idx, 0, M - 1)
        take = (s == n - 1) & (out_idx >= 0)
        outs = outs.at[oc].set(jnp.where(take, y, outs[oc]))
        state_next = lax.ppermute(y, axis_name, perm)
        return (state_next, outs), None

    (_, outs), _ = lax.scan(step, (state0, outs0),
                            jnp.arange(M + n - 1))
    # only the last stage wrote anything; psum replicates it ring-wide
    return lax.psum(outs, axis_name)


def pipeline_apply(stage_fn, stacked_params, x, mesh, num_microbatches=None,
                   axis="pp", batch_axis=None, param_specs=None):
    """Run x through S pipeline stages sharded over mesh axis `axis`.

    stage_fn(params, x_mb) -> y_mb must be shape-preserving (homogeneous
    stages — the classic pipeline regime). stacked_params: pytree with
    leading dim S == mesh.shape[axis] (see stack_stage_params). x: global
    [B, ...] batch, B divisible by num_microbatches (default S, the minimum
    that keeps every stage busy; more microbatches shrink the bubble
    fraction (S-1)/(M+S-1)). batch_axis: optional mesh axis ('dp') to
    additionally shard the microbatch dim — dp×pp composition on one mesh.

    param_specs: optional PartitionSpec pytree (same structure as
    stacked_params) overriding the default P(axis)-on-the-stage-dim
    placement — the dp×mp×pp composition hook: shard stage weights over
    BOTH 'pp' and a tensor-parallel axis (e.g. mlp_block_specs(
    tp_axis='mp', pp_axis='pp')) and have stage_fn do its own mp
    collectives (mlp_block_apply(..., tp_axis='mp')).

    Differentiable end to end; jit-compatible (call under the mesh).
    """
    S = mesh.shape[axis]
    leading = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if leading != S:
        raise ValueError(
            "stacked_params leading dim %d != pipeline size %d" %
            (leading, S))
    M = num_microbatches if num_microbatches is not None else S
    B = x.shape[0]
    if B % M:
        raise ValueError("batch %d not divisible into %d microbatches"
                         % (B, M))
    xs = x.reshape((M, B // M) + x.shape[1:])

    vary_axes = (axis,) if batch_axis is None else (axis, batch_axis)
    x_spec = P(None, batch_axis) if batch_axis else P()
    # jax 0.4.x GSPMD workaround (the pre-pvary era this repo's vary()
    # fallback targets): a stack/concatenate of replicated per-stage
    # params built INSIDE the jit, consumed by a shard_map slicing it
    # over `axis` on a MULTI-axis mesh (dp x pp), partitions wrong and
    # scales the pipeline output by a device-count factor. Pinning the
    # stacked tree replicated before the shard_map boundary restores
    # correct slicing; newer jax (pvary/pcast present) doesn't need it
    # and keeps the memory-scaling sliced placement.
    if len(mesh.shape) > 1 and not (hasattr(lax, "pcast")
                                    or hasattr(lax, "pvary")):
        from jax.sharding import NamedSharding
        rep = NamedSharding(mesh, P())
        stacked_params = jax.tree_util.tree_map(
            lambda a: lax.with_sharding_constraint(a, rep)
            if isinstance(a, jax.core.Tracer) else a, stacked_params)
    fn = shard_map(
        functools.partial(_pipeline_shard, stage_fn=stage_fn,
                          axis_name=axis, vary_axes=vary_axes),
        mesh=mesh,
        in_specs=(param_specs if param_specs is not None
                  else pipeline_stages_spec(stacked_params, axis), x_spec),
        out_specs=x_spec)
    out = fn(stacked_params, xs)
    return out.reshape((B,) + out.shape[2:])
