"""Expert parallelism (`ep` mesh axis): mixture-of-experts FFN.

TPU-first addition (the reference predates MoE entirely; SURVEY §2 commits
to DP/TP/PP/SP/EP composable on one Mesh). The design is the classic
static-shape TPU MoE (Shazeer-style dense dispatch, the pattern GShard
popularized): top-1 gating, fixed expert capacity, and dispatch/combine as
one-hot einsums — no ragged shapes, no host-side routing. Under GSPMD the
expert dim of the weights and the [E, C, D] dispatched activations are
sharded P('ep'); XLA lowers the dispatch einsum to the all-to-all over ICI,
exactly as a hand-written collective would, but fused and overlapped.

Everything is a pure jax function over an explicit params pytree —
differentiable, jit/pjit-friendly, composable with dp on the same mesh.
"""
import numpy as np

import jax
import jax.numpy as jnp

from .mesh import P, NamedSharding

__all__ = ["init_moe_params", "moe_layer", "moe_param_specs",
           "dense_reference"]


def init_moe_params(rng, d_model, d_hidden, num_experts, dtype="float32"):
    """params = {gate [D,E], w1 [E,D,H], b1 [E,H], w2 [E,H,D], b2 [E,D]}."""
    k = [rng.randn(d_model, num_experts) * 0.02,
         rng.randn(num_experts, d_model, d_hidden) * (d_model ** -0.5),
         np.zeros((num_experts, d_hidden)),
         rng.randn(num_experts, d_hidden, d_model) * (d_hidden ** -0.5),
         np.zeros((num_experts, d_model))]
    names = ["gate", "w1", "b1", "w2", "b2"]
    return {n: jnp.asarray(a, dtype) for n, a in zip(names, k)}


def moe_param_specs(axis="ep"):
    """PartitionSpecs: experts sharded over `axis`, gate replicated."""
    return {"gate": P(), "w1": P(axis), "b1": P(axis),
            "w2": P(axis), "b2": P(axis)}


def dense_reference(params, x):
    """Per-token expert compute without capacity limits (the semantics the
    capacity-bounded fast path approaches as capacity grows)."""
    logits = x @ params["gate"]                      # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)              # [N]
    top_p = jnp.max(probs, axis=-1)                  # [N]
    h = jnp.einsum("nd,edh->neh", x, params["w1"]) + params["b1"]
    h = jax.nn.relu(h)
    y = jnp.einsum("neh,ehd->ned", h, params["w2"]) + params["b2"]
    y_sel = jnp.take_along_axis(
        y, expert[:, None, None].repeat(y.shape[-1], -1), axis=1)[:, 0]
    return y_sel * top_p[:, None]


def moe_layer(params, x, capacity_factor=1.25, mesh=None, axis="ep"):
    """Top-1 MoE FFN over tokens x [N, D] -> ([N, D], aux_loss).

    Static shapes: each expert processes exactly C = ceil(N/E *
    capacity_factor) token slots; overflow tokens pass through with zero
    expert output (standard capacity dropping). aux_loss is the GShard
    load-balance term mean(fraction_tokens * fraction_probs) * E^2 — add
    a small multiple of it to the training loss to keep experts used.

    With `mesh` given, expert-dim intermediates are sharding-constrained to
    P(axis) so GSPMD dispatches tokens over the ep axis (all-to-all on
    ICI); without it the same code runs single-device.
    """
    n, d = x.shape
    e = params["w1"].shape[0]
    cap = int(np.ceil(n / e * capacity_factor))

    logits = x @ params["gate"]                      # [N, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)              # [N] int
    top_p = jnp.max(probs, axis=-1)                  # [N]

    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)   # [N, E]
    # position of each token within its expert's queue (0-based)
    pos = jnp.sum(jnp.cumsum(onehot, axis=0) * onehot, axis=-1) - 1.0  # [N]
    keep = pos < cap                                         # overflow drop
    pos_clip = jnp.clip(pos, 0, cap - 1).astype(jnp.int32)

    # dispatch/combine tensors (dense one-hots -> einsum == all_to_all)
    pos_onehot = jax.nn.one_hot(pos_clip, cap, dtype=jnp.float32)  # [N, C]
    dispatch = (onehot * keep[:, None])[:, :, None] * \
        pos_onehot[:, None, :]                               # [N, E, C]
    combine = dispatch * top_p[:, None, None]                # [N, E, C]

    expert_in = jnp.einsum("nec,nd->ecd", dispatch,
                           x.astype(jnp.float32))            # [E, C, D]
    if mesh is not None:
        expert_in = jax.lax.with_sharding_constraint(
            expert_in, NamedSharding(mesh, P(axis)))
    h = jnp.einsum("ecd,edh->ech", expert_in, params["w1"].astype(
        jnp.float32)) + params["b1"].astype(jnp.float32)[:, None, :]
    h = jax.nn.relu(h)
    out = jnp.einsum("ech,ehd->ecd", h, params["w2"].astype(
        jnp.float32)) + params["b2"].astype(jnp.float32)[:, None, :]
    # bias must not leak into empty slots (combine handles weighting, but
    # b2 made empty slots nonzero only matters through combine=0 -> fine)
    if mesh is not None:
        out = jax.lax.with_sharding_constraint(
            out, NamedSharding(mesh, P(axis)))
    y = jnp.einsum("nec,ecd->nd", combine, out)              # [N, D]

    # load-balance aux loss (GShard eq. 4): encourages uniform routing
    frac_tokens = jnp.mean(onehot, axis=0)                   # [E]
    frac_probs = jnp.mean(probs, axis=0)                     # [E]
    aux = jnp.sum(frac_tokens * frac_probs) * e
    return y.astype(x.dtype), aux
