"""Tensor (model) parallelism: Megatron-style column/row-parallel blocks
over a mesh ``mp`` axis, composable with the pipeline (``pp``) and data
(``dp``) axes on ONE Mesh (SURVEY.md §2 "DP/TP/PP/SP composable").

TPU-first design: the reference era's only model-partitioning story is the
pserver parameter split (python/paddle/fluid/distribute_transpiler.py),
which shards the *storage* of parameters, not the *math*. Here the math is
sharded: the first matmul is column-parallel (weight split on its output
dim, activations stay local), the second is row-parallel (weight split on
its input dim) followed by one ``psum`` over ``mp`` — the classic
two-matmul block with a single collective, riding ICI.

Two execution modes, same params + specs:

- GSPMD mode (no shard_map): apply with ``tp_axis=None``; place the
  params with ``mlp_block_specs()`` and let XLA insert the collectives.
- Manual mode (inside ``shard_map`` — e.g. a pipeline stage, where the
  ``pp`` schedule is already manual): apply with ``tp_axis="mp"``; the
  block psums explicitly. This is what makes dp×mp×pp composition work:
  ``pipeline_apply(param_specs=...)`` shards the stacked stage weights
  over BOTH 'pp' (stage dim) and 'mp' (hidden dim), and each stage runs
  this block with its local weight shards.
"""
import jax
import jax.numpy as jnp
from jax import lax

from .mesh import P

__all__ = ["mlp_block_init", "mlp_block_apply", "mlp_block_specs"]


def mlp_block_init(rng, d, d_hidden, scale=0.1):
    """Params for one tanh MLP block: [d -> d_hidden -> d] (shape-
    preserving, so it can serve as a homogeneous pipeline stage)."""
    k1, k2 = jax.random.split(jax.random.PRNGKey(rng) if isinstance(rng, int)
                              else rng)
    return {
        "w1": jax.random.normal(k1, (d, d_hidden), jnp.float32) * scale,
        "b1": jnp.zeros((d_hidden,), jnp.float32),
        "w2": jax.random.normal(k2, (d_hidden, d), jnp.float32) * scale,
        "b2": jnp.zeros((d,), jnp.float32),
    }


def mlp_block_specs(tp_axis="mp", pp_axis=None):
    """PartitionSpecs for (optionally stage-stacked) mlp_block params.

    Column-parallel w1/b1 split the hidden dim over ``tp_axis``; the
    row-parallel w2 splits its input (hidden) dim; b2 is replicated over
    mp (added after the psum). With ``pp_axis`` set, a leading stacked
    stage dim is sharded over it (pipeline composition)."""
    def pp(*rest):
        return P(pp_axis, *rest) if pp_axis else P(*rest)
    return {
        "w1": pp(None, tp_axis),
        "b1": pp(tp_axis),
        "w2": pp(tp_axis, None),
        "b2": pp(None),
    }


def mlp_block_apply(params, x, tp_axis=None):
    """y = w2ᵀ·tanh(w1ᵀx + b1) + b2, with the hidden dim sharded over
    ``tp_axis`` when running manually inside shard_map (one psum — the
    Megatron pattern). With tp_axis=None this is the dense math (use
    under GSPMD with mlp_block_specs placements, or as the single-chip
    reference)."""
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    z = h @ params["w2"]
    if tp_axis is not None:
        z = lax.psum(z, tp_axis)
    return z + params["b2"]
