"""ParallelExecutor: data-parallel training over a device mesh.

Parity: python/paddle/fluid/parallel_executor.py + paddle/fluid/framework/
parallel_executor.cc + details/ (SSA graph, NCCL allreduce op handles,
num_threads / allow_op_delay scheduling knobs).

TPU-native design: NO replicated programs, NO explicit allreduce. The same
whole-program XLA function the single-chip Executor builds is jitted
(pjit) under an explicit ShardingPlan (parallel/plan.py): feeds sharded
on the batch dim over the 'dp' mesh axis, params/optimizer state placed
per the plan — replicated in the reference-parity default, split 1/N
over the shard axis with `sharded_weight_update=True` (ZeRO-style,
arXiv:2004.13336: grads reduce-scatter onto the owning shard, the update
runs on the shard, params all-gather on use). XLA partitions the
computation and inserts the collectives over ICI automatically,
overlapping them with the backward pass (what the reference's
allow_op_delay tried to approximate by hand). The scheduling knobs are
accepted and ignored — XLA owns the schedule.
"""
import collections
import time as _time

import numpy as np

import jax
import jax.numpy as jnp

from ..core import lowering
from ..core.framework import default_main_program
from ..core.executor import (global_scope, _feed_signature,
                             _nan_inf_enabled, _array_safety_enabled,
                             convert_feeds, _cache_put_lru,
                             _jit_cache_capacity)
from ..core.utils import find_var as _find_var
from ..observability import trace as _otrace
from .mesh import data_parallel_mesh, replicated, batch_sharded, NamedSharding, P
from .plan import ShardingPlan, _match_accumulator_param  # noqa: F401
# (_match_accumulator_param re-exported: the fallback attribution moved
# into plan.py with the rest of the partitioner)


def _var_batch_leading(v):
    """True iff a feed var shards over the batch axis: its declared shape
    has a -1 (dynamic batch) leading dim. Fixed-leading-dim vars (record
    metadata, lookup tables) replicate instead. Single source of truth for
    both record validation and feed sharding."""
    shape = tuple(getattr(v, "shape", None) or ()) if v is not None else ()
    return not shape or shape[0] in (-1, None)


class ParallelExecutor(object):
    def __init__(self, use_cuda=None, loss_name=None, main_program=None,
                 num_threads=None, allow_op_delay=False, share_vars_from=None,
                 use_tpu=None, devices=None, mesh=None, param_shardings=None,
                 batch_axis=None, check_nan_inf=None,
                 sharded_weight_update=False, plan=None, shard_axis=None,
                 tp_axis=None):
        self._program = main_program if main_program is not None \
            else default_main_program()
        self._validated = set()  # strict-mode analysis cache (see run)
        if plan is not None:
            # the plan IS the distribution config: silently ignoring a
            # conflicting mesh/partitioner kwarg would split placement
            # across two meshes (state per plan.mesh, feeds per the
            # other) or drop overrides the caller thinks are in force
            if mesh is not None and mesh != plan.mesh:
                raise ValueError(
                    "plan= was built over mesh %r but mesh= is %r — "
                    "pass one or the other"
                    % (dict(plan.mesh.shape), dict(mesh.shape)))
            if param_shardings or sharded_weight_update \
                    or shard_axis is not None or tp_axis is not None:
                raise ValueError(
                    "plan= already decides param_shardings / "
                    "sharded_weight_update / shard_axis / tp_axis; "
                    "build the plan with those (ShardingPlan.build) "
                    "instead of passing both")
            if batch_axis is not None and batch_axis != plan.batch_axis:
                raise ValueError(
                    "plan= was built with batch_axis=%r but "
                    "batch_axis=%r was passed — the plan decides"
                    % (plan.batch_axis, batch_axis))
            mesh = plan.mesh
        self.mesh = mesh if mesh is not None else data_parallel_mesh(
            devices=devices)
        self._batch_axis = plan.batch_axis if plan is not None \
            else (batch_axis if batch_axis is not None else "dp")
        # The distribution plan (parallel/plan.py, ARCHITECTURE.md §21):
        # every param, gradient and optimizer accumulator gets a
        # PartitionSpec over the mesh. sharded_weight_update=True arms
        # the ZeRO-style assignment (Xu et al. 2020, arXiv:2004.13336):
        # params + accumulators split dim 0 over the shard axis, so GSPMD
        # turns the gradient all-reduce into reduce-scatter, each replica
        # updates only its 1/N shard, and the new weights all-gather on
        # use — optimizer-state memory drops ~N-fold. tp_axis="tp" arms
        # the intra-layer tensor-parallel per-family rule over that
        # mesh axis (ARCHITECTURE.md §23). Precedence inside the
        # partitioner: explicit param_shardings > ParamAttr mesh_axes
        # annotations (accumulators follow) > auto TP > auto ZeRO.
        # shard_axis defaults to the batch axis, or to the active
        # DeviceLayout's recorded shard axis when one is set (the
        # elastic-training handoff: a resharded cohort keeps the
        # snapshot's update-sharding axis).
        if plan is None:
            if shard_axis is None:
                from .distributed import active_layout
                lay = active_layout()
                shard_axis = getattr(lay, "shard_axis", None) \
                    if lay is not None else None
                if shard_axis is not None \
                        and shard_axis not in self.mesh.axis_names:
                    # INHERITED from the active DeviceLayout, not
                    # user-typed: an eval/aux executor over a plain dp
                    # mesh in an elastic process whose cohort shards
                    # over 'zero' must fall back leniently (like the
                    # batch-axis default), not trip the typo guard
                    shard_axis = None
            plan = ShardingPlan.build(
                self._program, self.mesh, batch_axis=self._batch_axis,
                shard_axis=shard_axis, shard_update=sharded_weight_update,
                overrides=param_shardings, tp_axis=tp_axis)
        self.plan = plan
        # legacy view: param name -> PartitionSpec for every var the plan
        # shards (or the caller pinned); anything absent is replicated
        self._param_shardings = plan.spec_map()
        self._cache = collections.OrderedDict()
        self.last_stats = {}  # guard stat channel (see Executor)
        # XLA:CPU collectives deadlock when several executions are in
        # flight at once (each rendezvous needs one thread per virtual
        # device; concurrent programs starve the pool and abort). Real TPU
        # collectives don't have this failure mode — only serialize
        # dispatch on the CPU (test/virtual-mesh) backend.
        self._sync_dispatch = jax.default_backend() == "cpu"
        self._check_nan_inf = _nan_inf_enabled(check_nan_inf)
        self._array_safety = _array_safety_enabled()
        self._scope = global_scope()
        if share_vars_from is not None:
            self._scope = share_vars_from._scope
        self._prefetcher = None  # core/dispatch.HostIoPrefetcher, armed
        # lazily by the first run(prefetch=True) on a reader-fed program
        self._has_read = {}  # (uid, version) -> program has `read` ops
        self._last_ready_t = None  # profiling: previous completion, for
        # the device-idle-gap column

    def _state_sharding(self, name):
        return self.plan.sharding_for(name)

    @property
    def device_count(self):
        return self.mesh.devices.size

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True,
            steps=1, fetch_reduce="stack", timeout=None, prefetch=False):
        """Sharded run; steps=K runs the K-step device-resident loop (see
        Executor.run): the scan composes with the GSPMD shardings — feeds
        stay batch-sharded per step, params keep their replicated / ZeRO
        (sharded_weight_update) / tensor-parallel layouts across the loop
        carry, and XLA still inserts the gradient collectives inside the
        loop body. One host sync per K steps per call.

        timeout=SECONDS arms the same hang watchdog Executor.run(timeout=)
        has: the dispatch runs on a monitored worker thread and raises
        DispatchTimeoutError past the deadline (device state then
        indeterminate — recover by rollback/abort, see
        paddle_tpu.resilience).

        prefetch=True pipelines the host-io prepass exactly like
        Executor.run(prefetch=True) — the next step's reader records
        pop, pad AND device_put (with their batch shardings) on a
        background stage while the current step executes; staged pops
        roll back exactly on fence/fault/checkpoint (ARCHITECTURE.md
        §22)."""
        if timeout is None:
            return self._run_impl(fetch_list, feed, feed_dict, return_numpy,
                                  steps, fetch_reduce, prefetch=prefetch)
        from ..core.dispatch import dispatch_with_deadline
        return dispatch_with_deadline(
            lambda cancelled, info: self._run_impl(
                fetch_list, feed, feed_dict, return_numpy, steps,
                fetch_reduce, cancelled=cancelled, info=info, sync=True,
                prefetch=prefetch),
            timeout, "ParallelExecutor.run dispatch")

    def _run_impl(self, fetch_list, feed=None, feed_dict=None,
                  return_numpy=True, steps=1, fetch_reduce="stack",
                  cancelled=None, info=None, sync=False, prefetch=False):
        # one trace per training step via the executors' ONE shared
        # wrapper (core/dispatch.run_step_traced), on the dispatching
        # thread (the watchdog worker in timeout mode — a wedge leaves
        # the step's spans open for the bundle). See Executor._run_impl.
        from ..core.dispatch import run_step_traced
        return run_step_traced(
            "pexe", cancelled,
            lambda tspan: self._run_traced(
                fetch_list, feed, feed_dict, return_numpy, steps,
                fetch_reduce, cancelled, info, sync, prefetch, tspan),
            devices=int(self.mesh.devices.size))

    def _run_traced(self, fetch_list, feed, feed_dict, return_numpy,
                    steps, fetch_reduce, cancelled, info, sync, prefetch,
                    tspan):
        feed = feed if feed is not None else (feed_dict or {})
        program = self._program
        scope = self._scope
        fetch_names = [f if isinstance(f, str) else f.name for f in fetch_list]
        steps = int(steps)
        if steps < 1:
            raise ValueError("steps must be >= 1, got %r" % (steps,))
        tspan.set(program=str(program._uid),
                  version=int(program._version), steps=steps)
        if fetch_reduce not in lowering.FETCH_REDUCE_POLICIES:
            raise ValueError("fetch_reduce must be one of %r, got %r"
                             % (lowering.FETCH_REDUCE_POLICIES, fetch_reduce))

        feed_arrays = convert_feeds(program, feed, host=True)

        # strict mode (FLAGS_validate_program): same pre-lowering static
        # verification Executor.run performs, plus the deployment tier
        # against the ARMED plan — a stale/mismatched ShardingPlan fails
        # here with a named entry instead of as a device_put shape error
        # per var mid-dispatch
        from ..core.executor import maybe_validate_program
        from ..analysis import DeploymentContext
        maybe_validate_program(
            program, feed_arrays, fetch_names, steps, self._validated,
            deploy=DeploymentContext.for_training(plan=self.plan,
                                                  steps=steps))

        if info is not None:
            # preliminary watchdog identity (refined after the prepass)
            info["cache_key"] = (program._uid, program._version,
                                 _feed_signature(feed_arrays),
                                 tuple(fetch_names))

        # pre-dispatch hooks (cluster fence + fault seam) via the shared
        # dispatch-guard choreography — before the io pre-pass and seed
        # draw, staged prefetch refunded on a hook raise (ONE copy with
        # Executor: core/dispatch.run_dispatch_hooks)
        from ..core import dispatch as _dispatch
        pf = self._prefetcher
        _dispatch.run_dispatch_hooks(program, steps, feed_arrays,
                                     prefetcher=pf, cancelled=cancelled)

        def _batch_leading(name):
            return _var_batch_leading(_find_var(program, name))

        # the batch dim shards over the batch axis only — a dp×sp/pp/ep
        # mesh must not demand divisibility by the full device count
        dp = self.mesh.shape.get(self._batch_axis, 1)

        def _check_divisible(arr, what):
            if np.shape(arr) and np.shape(arr)[0] % dp != 0:
                raise ValueError(
                    "batch size %d of %s must divide evenly across the "
                    "%d-way %r axis" % (np.shape(arr)[0], what, dp,
                                        self._batch_axis))

        for name, arr in feed_arrays.items():
            if _batch_leading(name):
                _check_divisible(arr, "feed %r" % name)
        # in-graph reader programs work data-parallel too: records pop
        # host-side and shard over the mesh like any feed (validated before
        # the record is consumed). Only batch-leading fields must divide
        # across devices; fixed-leading-dim fields replicate below.
        def _validate_record(rec, out_vars):
            for f, v in zip(rec, out_vars):
                if _var_batch_leading(v):
                    _check_divisible(
                        f, "reader record field %r" % getattr(v, "name", "?"))

        # host-io consume via the shared choreography (ONE copy with
        # Executor: staged-block identity check, mismatch refund, inline
        # prepass fallback, honest span closure)
        stacked_names = set()
        staged = _dispatch.consume_host_io(
            self, program, scope, steps, True, cancelled, feed_arrays,
            stacked_names, tspan, validate=_validate_record)
        if staged is _dispatch.CANCELLED:
            return None  # watchdog deadline raised on the caller
        feed_names = sorted(feed_arrays)

        def _sharding_for(name, ndim, stacked):
            if _batch_leading(name):
                # stacked reader feeds carry a leading K (time) axis; their
                # batch dim moved to position 1 — the scan slices K off and
                # each step sees the usual batch-dim-0 sharding
                return batch_sharded(self.mesh, ndim,
                                     axis_name=self._batch_axis,
                                     batch_dim=1 if name in stacked
                                     else 0)
            return replicated(self.mesh)

        def _feed_sharding(name, ndim):
            return _sharding_for(name, ndim, stacked_names)

        # every trace-time env flag (conv layout, flash dispatch, remat
        # tuning) is traced into the fn — key on them so an env-var flip
        # re-traces instead of serving the other configuration. (steps,
        # fetch_reduce, stacked feeds) shape the traced loop the same way.
        from ..core import compile_cache
        from ..core.lowering import trace_env_key
        unroll = lowering.resolve_multistep_unroll(
            self.mesh.devices.flat[0].platform) if steps > 1 else False
        multi_sig = (steps, fetch_reduce if steps > 1 else None, unroll,
                     tuple(sorted(stacked_names)))
        key = (program._uid, program._version,
               _feed_signature(feed_arrays), tuple(fetch_names),
               trace_env_key(), multi_sig)
        if info is not None:
            info["cache_key"] = key
        def build_jitted(state_rw, state_ro, state_out, donate):
            rep = replicated(self.mesh)
            in_shardings = (
                [_feed_sharding(n, feed_arrays[n].ndim)
                 for n in feed_names],
                [self._state_sharding(n) for n in state_rw],
                [self._state_sharding(n) for n in state_ro],
                rep,
            )
            out_shardings = (rep,
                             [self._state_sharding(n) for n in state_out],
                             rep)
            # the plan's gradient constraints pin each sharded param's
            # grad to the owner's shard layout inside the traced step, so
            # GSPMD lowers the cross-replica gradient sum as
            # reduce-scatter straight onto the updating shard; the
            # tensor-parallel gather constraints pin each TP param's
            # traced value replicated at the step's entry (weights
            # sharded at rest, all-gathered on use — bit-exact compute,
            # ARCHITECTURE.md §23). Param names and grad names never
            # collide (GRAD_SUFFIX), so one dict carries both.
            constraints = dict(self.plan.grad_constraints())
            constraints.update(self.plan.param_gather_constraints())
            constraints = constraints or None
            if steps > 1:
                fn = lowering.lower_multi_step(
                    program, feed_names, fetch_names, state_rw,
                    state_ro, state_out, steps,
                    fetch_reduce=fetch_reduce,
                    stacked_feed_names=stacked_names, mesh=self.mesh,
                    unroll=unroll, shard_constraints=constraints)
            else:
                fn = lowering.build_program_fn(
                    program, feed_names, fetch_names, state_rw,
                    state_ro, state_out, mesh=self.mesh,
                    collect_errors=True, shard_constraints=constraints)
            return jax.jit(fn, in_shardings=in_shardings,
                           out_shardings=out_shardings,
                           donate_argnums=(1,) if donate else ())

        def aot_key():
            # the sharded executable is keyed on everything that shapes
            # it beyond the Executor signature — mesh topology, axis
            # names, and the FULL ShardingPlan in canonical JSON
            # (serialized executables bake the partitioning in; any plan
            # change — a different shard axis, one var's override — is a
            # different executable and must be a different key)
            aot_dir = compile_cache.active_aot_cache_dir()
            if aot_dir is None:
                return None, None
            return aot_dir, compile_cache.aot_entry_key(
                program, _feed_signature(feed_arrays),
                tuple(fetch_names), trace_env_key(), multi_sig,
                self.mesh.devices.flat[0],
                extra={
                    "executor": "parallel",
                    "num_devices": int(self.mesh.devices.size),
                    "mesh_axes": {a: int(s) for a, s in
                                  self.mesh.shape.items()},
                    # the concrete span, in mesh order: two replicas of
                    # one model over DIFFERENT device spans must store
                    # separate artifacts (see aot_entry_key device_id)
                    "mesh_device_ids": [int(getattr(d, "id", -1))
                                        for d in self.mesh.devices.flat],
                    "batch_axis": self._batch_axis,
                    "plan": self.plan.to_json(),
                })

        compiled = False
        aot_hit = False
        aot_saved = 0.0
        aot_compile_s = 0.0  # eager lower+compile time paid THIS call
        aot_entry = None  # (dir, key_hash) when loaded from disk
        entry = self._cache.get(key)
        if entry is not None:
            self._cache.move_to_end(key)  # LRU touch
        else:
            state_rw, state_ro, state_out = lowering.analyze_state(
                program, feed_names, fetch_names)
            aot_dir, akey = aot_key()
            executable = None
            if akey is not None:
                loaded = compile_cache.aot_load(aot_dir, *akey)
                if loaded is not None:
                    executable, aot_saved = loaded
                    aot_hit = True
                    aot_entry = (aot_dir, akey[0])
            if executable is None:
                compiled = True
                if akey is not None:
                    try:
                        t0c = _time.perf_counter()

                        # serialized artifacts compile WITHOUT donation
                        # (deserialized input-output aliasing corrupts
                        # the heap — see Executor._run_impl). Lower from
                        # AVALS, not live values: scope arrays may still
                        # be committed to a DIFFERENT plan's layout
                        # (fresh executor over a scope another plan
                        # trained — the elastic-reshard handoff), and
                        # lowering committed arrays against conflicting
                        # explicit in_shardings raises, silently
                        # forfeiting the artifact; the in_shardings
                        # alone decide placement.
                        def _aval(v):
                            return jax.ShapeDtypeStruct(
                                np.shape(v),
                                getattr(v, "dtype", None)
                                or np.asarray(v).dtype)

                        comp = build_jitted(
                            state_rw, state_ro, state_out,
                            donate=False).lower(
                            [_aval(feed_arrays[n]) for n in feed_names],
                            [_aval(scope.get(n)) for n in state_rw],
                            [_aval(scope.get(n)) for n in state_ro],
                            jax.ShapeDtypeStruct((), np.uint32)).compile()
                        aot_compile_s = _time.perf_counter() - t0c
                        if compile_cache.aot_store(
                                aot_dir, akey[0], akey[1], comp,
                                aot_compile_s):
                            executable = comp
                        # store failed: no artifact on disk, so keep
                        # donation (see Executor._run_impl)
                    except Exception:  # noqa: BLE001 — cache is
                        pass           # best-effort; jit path raises
                if executable is None:
                    executable = build_jitted(state_rw, state_ro,
                                              state_out, donate=True)
            entry = (executable, state_rw, state_ro, state_out)
            _cache_put_lru(self._cache, key, entry, _jit_cache_capacity())
        jitted, state_rw, state_ro, state_out = entry

        def read_state(names, commit=False):
            vals = []
            for n in names:
                v = scope.get(n)
                if v is None:
                    raise RuntimeError(
                        "persistable var %r not initialized; run the startup "
                        "program with Executor first" % n)
                want = self._state_sharding(n)
                if not (isinstance(v, jax.Array) and v.sharding == want):
                    v = jax.device_put(v, want)
                    if commit:
                        # commit the re-placed value to the scope so the
                        # at-rest layout IS the plan's: read-only state
                        # (inference params on a TP serving mesh, the LR
                        # var) would otherwise keep its full host/loader
                        # copy forever and re-pay the transfer+reshard
                        # every dispatch — for a sharded-at-rest plan
                        # the scope copy is THE 1/N residency claim.
                        # Never for rw state: those buffers are donated,
                        # and a committed-then-donated array would leave
                        # the scope holding a deleted buffer if the
                        # dispatch raises before the post-step
                        # write-back (the original host copy survives
                        # that today).
                        scope.set(n, v)
                vals.append(v)
            return vals

        feed_vals = [jax.device_put(
            feed_arrays[n], _feed_sharding(n, feed_arrays[n].ndim))
            for n in feed_names]

        seed = jnp.asarray(np.uint32(
            scope.next_seed() if steps == 1
            else scope.next_seed_block(steps)))
        from .. import profiler as _prof
        profiling = _prof.is_active()

        def _donating_call_guard(fn_obj):
            # a donating jit must never compile through the jax
            # persistent HLO cache: warm-cache deserialization breaks
            # donation in this jax (silently wrong numerics — see
            # compile_cache.donating_multidevice_compile_guard). Every
            # call of a plain-jit entry is guarded, not just the first:
            # a plain jit also RETRACES silently when state avals drift
            # under an unchanged key, and a first call that failed
            # leaves the entry cached with its compile still pending —
            # both would otherwise compile unguarded. The guard is a
            # refcounted pair of free config flips (measured ~1µs) on
            # the cache-enabled path and a no-op otherwise; AOT
            # artifacts (jax.stages.Compiled) are donation-free and
            # never guarded.
            import contextlib
            if not isinstance(fn_obj, jax.stages.Compiled):
                return compile_cache.donating_multidevice_compile_guard()
            return contextlib.nullcontext()

        # device-enqueue span (async; see Executor) — open = wedged here
        dsp = tspan.child("exec/dispatch")
        t0 = _time.perf_counter() if profiling else 0.0

        def _call(fn_obj):
            with _donating_call_guard(fn_obj):
                return fn_obj(feed_vals, read_state(state_rw),
                              read_state(state_ro, commit=True), seed)

        def _find_aot_entry():
            aot_dir_, akey_ = aot_key()
            return (aot_dir_, akey_[0]) if akey_ is not None else None

        def _rebuild():
            # fresh donating jit — see call_with_aval_fallback
            fresh = build_jitted(state_rw, state_ro, state_out,
                                 donate=True)
            _cache_put_lru(self._cache, key,
                           (fresh, state_rw, state_ro, state_out),
                           _jit_cache_capacity())
            return fresh

        (fetches, new_state, errors), fell_back = \
            _dispatch.call_with_aval_fallback(
                _call, jitted, aot_entry, _find_aot_entry, _rebuild)
        if fell_back:
            compiled, aot_hit, aot_saved, aot_entry = \
                True, False, 0.0, None
        # sentinel stat tap: peel float statistics (grad norm) off the
        # error dict before any error sync (see Executor._run_impl)
        from ..core.executor import pop_guard_stats
        self.last_stats = pop_guard_stats(errors)
        dsp.end(compiled=compiled, aot_hit=aot_hit)
        if cancelled is not None and cancelled.is_set():
            # caller already raised DispatchTimeoutError; a late scope
            # write would race its rollback (see Executor._run_impl)
            return None
        if sync:
            # watchdog mode: device-sync BEFORE the scope write-back so
            # an execution-phase hang can't park unresolved arrays in
            # the scope (see Executor._run_impl)
            wsp = tspan.child("exec/watchdog_sync")
            jax.block_until_ready((fetches, new_state))
            wsp.end()
            if cancelled is not None and cancelled.is_set():
                return None
        # state write-back precedes any raise point (incl. the sync below):
        # rw inputs were donated (see Executor.run)
        for n, v in zip(state_out, new_state):
            scope.set(n, v)
        # pipelined dispatch: stage the NEXT step's reader block (pop,
        # pad, sharded device_put) while this step's device work — and
        # the CPU-backend collective sync below — proceeds
        if prefetch:
            def _stage(arrays, stacked):
                # the prefetched feeds' H2D happens HERE, on the
                # staging thread, already in their batch shardings —
                # the dispatch thread's device_put then sees an
                # identically-sharded array (no transfer)
                for n, a in list(arrays.items()):
                    arrays[n] = jax.device_put(
                        a, _sharding_for(n, np.ndim(a), stacked))

            pf = _dispatch.kick_next_prepass(
                self, program, scope, steps, True, cancelled, "pexe",
                validate=_validate_record, stage_fn=_stage)
        def _sync_extra():
            if self._sync_dispatch and not sync:
                _prof.note_sync("pexe/cpu_collective_serialize")
                jax.block_until_ready((fetches, new_state))
            if profiling:
                tag = "pexe_program_%s(v%d)x%d fetch=%s" % (
                    program._uid, program._version, self.device_count,
                    ",".join(fetch_names) or "-")
                _dispatch.profile_dispatch(
                    self, tag, "pexe/profiling", t0,
                    (fetches, new_state), compiled, aot_hit, aot_saved,
                    aot_compile_s)

        # guard-flag raise + FLAGS_check_nan_inf sweep + refund-on-raise
        # via the shared post-dispatch choreography (ONE copy with
        # Executor: core/dispatch.run_post_dispatch_checks)
        _dispatch.run_post_dispatch_checks(
            errors, fetches, fetch_names, new_state, state_out,
            self._array_safety, self._check_nan_inf,
            "ParallelExecutor.run", prefetcher=pf, cancelled=cancelled,
            sync_fn=_sync_extra)
        if return_numpy:
            _prof.note_sync("pexe/return_numpy")
            with tspan.child("exec/d2h"):
                return [np.asarray(f) for f in fetches]
        from ..core.executor import FetchHandle
        return [FetchHandle(f) for f in fetches]
