"""Mandatory exclusive-client guard for the TPU (axon tunnel) backend.

The axon tunnel wedges its lease when two JAX clients overlap — this cost
rounds 2-4 multi-hour outages, twice in round 4 alone even though
``tools/tpu_lock.sh`` existed, because the lock was advisory (prose rules
don't stop ad-hoc scripts).  This module makes the lock MANDATORY in code:
importing ``paddle_tpu`` wraps ``jax._src.xla_bridge._init_backend`` so
that initializing any non-CPU platform first acquires the same flock
``tools/tpu_lock.sh`` uses (``/tmp/tpu_client.lock``).

Semantics (chosen so the bench driver, which runs ``python bench.py`` with
no wrapper, can never be locked out by a background probe):

- CPU-only runs (``JAX_PLATFORMS=cpu`` — the test suite, the multichip
  dryrun) never touch the lock.
- If the lock is free: take it and hold it for the life of the process
  (released by the OS at exit, crash included).
- If an ancestor already holds it (``tools/tpu_lock.sh`` sets
  ``PTPU_LOCK_HELD=1`` and the flock fd is inherited): proceed.
- Otherwise BLOCK up to ``PTPU_LOCK_TIMEOUT`` seconds (default 1200 —
  matches tpu_lock.sh) waiting for the other client to finish, then raise
  ``TPULockTimeout``.  A stray second client therefore gets a Python
  exception, not a wedged tunnel lease.

Escape hatch: ``PTPU_LOCK_DISABLE=1`` (single-tenant environments).

Parity note: the reference serializes GPU access per-process through the
CUDA context + nccl communicator setup (paddle/fluid/platform/device_context.cc);
a remote-tunnel TPU needs the serialization at the *host* level instead,
which is what this flock provides.
"""
import errno
import fcntl
import json
import os
import time

LOCKFILE = "/tmp/tpu_client.lock"

_lock_fd = None          # held for process lifetime once acquired
_installed = False


class TPULockTimeout(BaseException):
    """Deliberately NOT an Exception: jax's multi-platform fallback wraps
    backend init in ``except Exception`` and would otherwise fall back to
    CPU — turning "second TPU client" into silently-wrong CPU benchmark
    numbers.  A lock timeout must abort the process, not downgrade it."""


def cpu_only_env():
    """True when JAX_PLATFORMS explicitly restricts this process to CPU
    (test suite / smoke runs) — such a process never needs the lock."""
    want = os.environ.get("JAX_PLATFORMS", "")
    parts = [p.strip() for p in want.split(",") if p.strip()]
    return bool(parts) and all(p == "cpu" for p in parts)


def acquire_tpu_lock(timeout=None):
    """Idempotently acquire the exclusive TPU-client flock.

    Returns immediately if already held by this process or an ancestor
    (PTPU_LOCK_HELD, set by tools/tpu_lock.sh).  Blocks up to ``timeout``
    seconds (default $PTPU_LOCK_TIMEOUT or 1200) otherwise.
    """
    global _lock_fd
    if _lock_fd is not None:
        return
    if os.environ.get("PTPU_LOCK_DISABLE") == "1":
        return
    if os.environ.get("PTPU_LOCK_HELD") == "1":
        # Ancestor (tools/tpu_lock.sh) claims to hold it via an inherited
        # flock fd.  Verify rather than trust: if the lock is actually
        # FREE the claim is stale (e.g. a backgrounded child outlived the
        # flock wrapper) — take it ourselves.  If it is held we cannot
        # distinguish ancestor from stranger, so honor the claim.
        fd = os.open(LOCKFILE, os.O_CREAT | os.O_RDWR, 0o666)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            _lock_fd = fd  # stale claim; now genuinely held
        except OSError:
            os.close(fd)   # held (presumably by the ancestor): proceed
        return
    if timeout is None:
        timeout = float(os.environ.get("PTPU_LOCK_TIMEOUT", "1200"))
    fd = os.open(LOCKFILE, os.O_CREAT | os.O_RDWR, 0o666)
    deadline = time.monotonic() + timeout
    notified = False
    while True:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            _lock_fd = fd  # hold until process exit
            return
        except OSError:
            if time.monotonic() >= deadline:
                os.close(fd)
                raise TPULockTimeout(
                    "another TPU client holds %s (waited %.0fs). The axon "
                    "tunnel wedges on concurrent clients; run under "
                    "tools/tpu_lock.sh or wait for the other client."
                    % (LOCKFILE, timeout))
            if not notified:
                import sys
                print("tpu_guard: %s busy; waiting up to %.0fs for the "
                      "other TPU client..." % (LOCKFILE, timeout),
                      file=sys.stderr)
                notified = True
            time.sleep(2.0)


def accelerator_missing():
    """True when this process was meant for the accelerator but jax
    initialized only CPU devices (tunnel down / backend init error →
    jax's silent CPU fallback).  False under JAX_PLATFORMS=cpu."""
    if cpu_only_env():
        return False
    import jax
    return all(d.platform == "cpu" for d in jax.devices())


def require_accelerator(tool_name):
    """Loud-failure rule for benchmark emitters: abort instead of emitting
    CPU timings dressed up as TPU data.  No-op under JAX_PLATFORMS=cpu."""
    if accelerator_missing():
        import sys
        sys.exit("%s: accelerator expected but only CPU devices "
                 "initialized; refusing to emit CPU numbers" % tool_name)


# ---------------------------------------------------------------------------
# Bounded window locks with stale-holder recovery (PR 19, benchd).
#
# acquire_tpu_lock() above holds for process LIFETIME — right for a
# one-shot bench run, wrong for a resident daemon that must release the
# tunnel between hardware windows.  WindowLock is the bounded variant:
# acquire at window open, release at window close.
#
# Stale-holder recovery: flock itself auto-releases on process death, so
# a plain flock can't go stale — but an fd INHERITED by a forgotten
# child (a sweep's backgrounded subprocess surviving a SIGKILLed
# tpu_lock.sh wrapper) holds the flock with no live holder recorded.
# Mirroring checkpoint/snapshot.py clean_stale_tmp, the holder writes
# ``{"pid": ..., "owner": ..., "ts": ...}`` into the lockfile on
# acquire and truncates it on clean release; a contender that finds the
# lock held AND the recorded pid dead breaks the lock by unlinking the
# file and retrying on a fresh inode (the dead holder's flock pins only
# the old, now-unreachable inode).  A live recorded pid — or an
# unparseable/empty lockfile (can't prove staleness) — is always
# honored.
# ---------------------------------------------------------------------------

class WindowLock(object):
    """A held window lock: release() truncates the holder record and
    drops the flock.  Usable as a context manager."""

    def __init__(self, fd, path):
        self.fd = fd
        self.path = path

    def release(self):
        if self.fd is None:
            return
        fd, self.fd = self.fd, None
        try:
            os.ftruncate(fd, 0)
        except OSError:
            pass
        os.close(fd)  # drops the flock

    @property
    def held(self):
        return self.fd is not None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return "WindowLock(%s, %s)" % (self.path,
                                       "held" if self.held else "released")


def _lock_holder_pid(path):
    """The pid recorded in the lockfile, or None when absent/unparseable
    (prose in the lockfile proves nothing — hands off)."""
    try:
        with open(path, "r") as f:
            data = json.loads(f.read() or "null")
    except (OSError, ValueError):
        return None
    if isinstance(data, dict) and isinstance(data.get("pid"), int):
        return data["pid"]
    return None


def break_stale_lock(path=LOCKFILE):
    """Unlink `path` iff its recorded holder pid is provably dead —
    the clean_stale_tmp liveness idiom: ProcessLookupError = dead (safe
    to break), PermissionError = alive under another uid (honor),
    no/any-other evidence = honor.  Returns True when broken."""
    pid = _lock_holder_pid(path)
    if pid is None or pid == os.getpid():
        return False
    try:
        os.kill(pid, 0)
        return False          # alive, same uid
    except ProcessLookupError:
        pass                  # provably dead — break below
    except PermissionError:
        return False          # alive, another uid
    except OSError:
        return False
    try:
        os.unlink(path)
        return True
    except OSError:
        return False


def acquire_window_lock(path=LOCKFILE, timeout=0.0, owner="benchd",
                        poll_s=0.5):
    """Acquire the client lock for a bounded window.  Returns a
    WindowLock, or None when the lock stayed busy past `timeout`
    seconds (a live client is measuring — the caller waits for the
    next window, it never queues behind hardware time).

    On contention the recorded holder's liveness is checked first: a
    dead holder's lockfile is broken (unlinked) and the acquire retried
    on the fresh inode, so a SIGKILLed sweep whose orphaned child pins
    the old flock cannot wedge every future window.
    """
    deadline = time.monotonic() + max(0.0, float(timeout))
    while True:
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o666)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as e:
            os.close(fd)
            if e.errno not in (errno.EAGAIN, errno.EACCES):
                raise
            if break_stale_lock(path):
                continue      # fresh inode now; retry immediately
            if time.monotonic() >= deadline:
                return None
            time.sleep(poll_s)
            continue
        # Locked — but only the inode this fd points at.  If a stale-
        # breaker unlinked the path between our open and flock, the
        # path now names a DIFFERENT inode (or none) and our lock
        # guards nothing: retry on the current file.
        try:
            st_fd = os.fstat(fd)
            st_path = os.stat(path)
            same = (st_fd.st_ino == st_path.st_ino
                    and st_fd.st_dev == st_path.st_dev)
        except OSError:
            same = False      # path unlinked beneath us
        if not same:
            os.close(fd)
            continue
        record = json.dumps({"pid": os.getpid(), "owner": str(owner),
                             "ts": time.time()})
        os.ftruncate(fd, 0)
        os.pwrite(fd, record.encode("utf-8"), 0)
        return WindowLock(fd, path)


def install():
    """Wrap jax's backend initialization so any non-CPU platform init
    first acquires the exclusive client lock.  Idempotent."""
    global _installed
    if _installed:
        return
    try:
        from jax._src import xla_bridge as xb
        orig = xb._init_backend
    except Exception:
        # Private jax API moved: degrade to best-effort (explicit
        # acquire_tpu_lock() calls in bench/tools still protect the
        # tunnel) rather than making the whole package unimportable.
        import warnings
        warnings.warn("tpu_guard: jax backend-init hook unavailable; "
                      "TPU-client lock is explicit-only in this process")
        return

    def _guarded_init_backend(platform, *a, **kw):
        global _lock_fd
        if platform in ("cpu",):
            return orig(platform, *a, **kw)
        had_lock = _lock_fd is not None
        acquire_tpu_lock()
        try:
            return orig(platform, *a, **kw)
        except BaseException:
            # Init failed (tunnel down, plugin error): a process that is
            # about to fall back to CPU must not keep the exclusive TPU
            # lock for its whole life.
            if not had_lock and _lock_fd is not None:
                os.close(_lock_fd)
                _lock_fd = None
            raise

    xb._init_backend = _guarded_init_backend
    _installed = True


install()
