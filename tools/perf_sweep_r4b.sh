#!/bin/bash
# DEPRECATED SHIM (PR 19): the round-4b sweep script (the first
# cheapest-first banked sweep, whose BENCH_r01 line is still the
# driver-series last-good baseline) was superseded by r4c/r5/r6 and
# finally by the declarative tier queue in paddle_tpu/benchd/tiers.py.
# Kept as a shim so stale references still bank through the store.
set -u
cd "$(dirname "$0")/.."
exec python tools/ptpu_bench.py run --git-bank "$@"
