#!/bin/bash
# Round-4 second-window sweep: ONLY the configs that failed or never ran
# while the tunnel was wedged (01:15-01:52Z failures all predate the flash
# Mosaic fix at 01:33Z or were undiagnosable because stderr went to
# /dev/null). Differences vs perf_sweep.sh:
#   - stderr is KEPT per run (/tmp/bench_err_N.log) so a failure is
#     diagnosable without re-burning tunnel time
#   - already-banked configs are not re-run
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/perf_sweep_r4b.log
: > $LOG
WEDGED=0
N=0
LOCK="tools/tpu_lock.sh"
tunnel_ok() {
  bash "$LOCK" timeout 120 python -c "import jax; print(jax.devices())" \
    >/dev/null 2>&1
}
probe() {
  [ "$WEDGED" = 1 ] && return 1
  tunnel_ok && return 0
  local rc=$?
  if [ $rc -eq 75 ]; then
    echo "- $(date -u +%FT%TZ) r4b sweep stopped: tpu_lock busy (rc=75)" >> BENCH_LOG.md
  else
    echo "- $(date -u +%FT%TZ) tunnel probe FAILED mid-r4b-sweep" >> BENCH_LOG.md
  fi
  WEDGED=1
  return 1
}
bank() {
  git commit -q -m "perf sweep: bank measured bench lines" \
    -- BENCH_LOG.md 2>/dev/null || true
}
run() {
  [ "$WEDGED" = 1 ] && { echo "skip (wedged): $*" | tee -a $LOG; return; }
  N=$((N+1))
  echo "=== [$N] $*" | tee -a $LOG
  local line rc
  bash "$LOCK" env "$@" BENCH_DEVICE_TIMEOUT=300 timeout -k 10 1200 \
    python bench.py >/tmp/bench_run.out 2>/tmp/bench_err_$N.log
  rc=$?
  if [ $rc -eq 75 ]; then
    echo "- $(date -u +%FT%TZ) r4b sweep stopped mid-run: tpu_lock busy" >> BENCH_LOG.md
    WEDGED=1
    return
  fi
  line=$(tail -1 /tmp/bench_run.out)
  echo "$line" | tee -a $LOG
  case "$line" in
    *'"error"'*|"")
      echo "- $(date -u +%FT%TZ) FAILED(rc=$rc, err=/tmp/bench_err_$N.log): $*" >> BENCH_LOG.md
      tail -3 /tmp/bench_err_$N.log >> $LOG
      case "$line" in
        *"device init"*) WEDGED=1 ;;
        "") tunnel_ok || WEDGED=1 ;;
      esac ;;
    *) printf -- '- %s `%s`\n  `%s`\n' "$(date -u +%FT%TZ)" "$*" "$line" \
         >> BENCH_LOG.md
       bank ;;
  esac
}
echo "- $(date -u +%FT%TZ) TUNNEL RECOVERED (probe rc=0 at 03:15Z); r4b sweep of previously-failed configs starts" >> BENCH_LOG.md
probe || exit 1
# flash's regime: long sequence. 01:19Z failure predates the Mosaic fix.
run BENCH_MODEL=transformer BENCH_BATCH=4 BENCH_SEQ=2048 BENCH_STEPS=5 BENCH_WARMUP=2
probe && run BENCH_MODEL=transformer BENCH_BATCH=4 BENCH_SEQ=2048 BENCH_STEPS=5 BENCH_WARMUP=2 BENCH_FUSED_ATTN=0
# pallas microbench: 01:15Z failure predates the Mosaic fix
if probe; then
  echo "=== pallas microbench" | tee -a $LOG
  bash "$LOCK" timeout 900 python tools/pallas_microbench.py \
    2>/tmp/bench_err_micro.log | tee -a $LOG | \
    while read -r line; do
      printf -- '- %s microbench `%s`\n' "$(date -u +%FT%TZ)" "$line" >> BENCH_LOG.md
    done
  [ "${PIPESTATUS[0]:-0}" = 0 ] || \
    echo "- $(date -u +%FT%TZ) FAILED: pallas_microbench (err=/tmp/bench_err_micro.log)" >> BENCH_LOG.md
  bank
fi
# latency-hiding flag: the 01:11Z invocation mis-quoted XLA_FLAGS (empty
# first token); pass it as ONE token this time
probe && run BENCH_BATCH=256 BENCH_DTYPE=bf16 \
  XLA_FLAGS=--xla_tpu_enable_latency_hiding_scheduler=true
# big compiles dead-last
probe && run BENCH_BATCH=512 BENCH_DTYPE=bf16 BENCH_STEPS=10 BENCH_WARMUP=3 BENCH_REMAT=1
probe && run BENCH_BATCH=1024 BENCH_DTYPE=bf16 BENCH_STEPS=10 BENCH_WARMUP=3 BENCH_REMAT=1
bank
echo "=== r4b sweep done (wedged=$WEDGED) ===" | tee -a $LOG
