#!/bin/bash
# Round-6 sweep: multi-step device-resident execution (PR 1). SUPERSEDES
# perf_sweep_r5.sh as the NEXT_SWEEP target; r5's queue ran (or stays in
# the historical record if the tunnel never healed). Cheapest-first; ONE
# client at a time via tools/tpu_lock.sh; rc-gated banking; stderr kept
# per run. Exits nonzero when wedged so the probe loop leaves the sweep
# queued for the next healthy window.
#
# What r6 measures (BENCH_MULTISTEP, Executor.run(steps=K)):
# - the TPU lax.scan K-step loop vs single-step dispatch, same configs —
#   the dispatch-overhead win every later kernel PR is stacked on top of.
#   CPU reference (2026-08-04, tunnel wedged): +65% tok/s at K=8 on the
#   dispatch-bound tiny transformer; parity on compute-bound resnet50.
# - K sensitivity (8/32) and fetch_reduce is 'last' in bench.py, so the
#   JSON "multistep" field labels every line.
# - one FLAGS_multistep_unroll=1 line: full unroll ALSO lets XLA fuse
#   across step boundaries on TPU; worth one compile to know.
# - re-queued 2026-08-05 with tier 2b (BENCH_SHARDED, PR 9): replicated
#   vs ZeRO-style sharded weight update on the real multi-chip mesh —
#   steps/s both legs + per-chip update-state bytes from the plan's
#   memory accounting + the fetch-divergence column. CPU reference
#   (8 virtual devices, 2-layer dim-256 Adam MLP): sharded ~2.1x
#   steps/s of replicated (update math on 1/8 shards beats 8x
#   redundant updates even with the gathers), update-state bytes/chip
#   ratio 0.125, divergence 2.4e-7 (ulp-level reduction-tree
#   difference, see test_bench_sharded_smoke).
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/perf_sweep_r6.log
: > $LOG
WEDGED=0
N=0
LOCK="tools/tpu_lock.sh"
tunnel_ok() {
  bash "$LOCK" timeout 120 python -c \
    'import jax,sys; sys.exit(0 if any(d.platform!="cpu" for d in jax.devices()) else 1)' \
    >/dev/null 2>&1
}
probe() {
  [ "$WEDGED" = 1 ] && return 1
  tunnel_ok && return 0
  local rc=$?
  if [ $rc -eq 75 ]; then
    echo "- $(date -u +%FT%TZ) r6 sweep stopped: tpu_lock busy (rc=75)" >> BENCH_LOG.md
  else
    echo "- $(date -u +%FT%TZ) tunnel probe FAILED mid-r6-sweep" >> BENCH_LOG.md
  fi
  WEDGED=1
  return 1
}
bank() {
  git commit -q -m "perf sweep: bank measured bench lines" \
    -- BENCH_LOG.md 2>/dev/null || true
}
run() {  # run <timeout_s> ENV=V...
  [ "$WEDGED" = 1 ] && { echo "skip (wedged): $*" | tee -a $LOG; return; }
  local to=$1; shift
  N=$((N+1))
  echo "=== [$N] $*" | tee -a $LOG
  local line rc
  bash "$LOCK" env "$@" BENCH_DEVICE_TIMEOUT=300 timeout -k 10 "$to" \
    python bench.py >/tmp/bench_run.out 2>/tmp/bench_err_r6_$N.log
  rc=$?
  if [ $rc -eq 75 ]; then
    echo "- $(date -u +%FT%TZ) r6 sweep stopped mid-run: tpu_lock busy" >> BENCH_LOG.md
    WEDGED=1
    return
  fi
  line=$(tail -1 /tmp/bench_run.out)
  if [ $rc -ne 0 ]; then
    line='{"error": "rc='$rc'"}'"$line"
  fi
  case "$line" in
    *'"error"'*|"")
      echo "- $(date -u +%FT%TZ) FAILED(rc=$rc, err=/tmp/bench_err_r6_$N.log): $*" >> BENCH_LOG.md
      tail -3 /tmp/bench_err_r6_$N.log >> $LOG
      case "$line" in
        *"device init"*) WEDGED=1 ;;
        *) tunnel_ok || WEDGED=1 ;;
      esac ;;
    *) printf -- '- %s `%s`\n  `%s`\n' "$(date -u +%FT%TZ)" "$*" "$line" \
         >> BENCH_LOG.md
       bank ;;
  esac
}
# --- tier 1: single-step baselines for the day (cheap, known compiles) -----
probe && run 900 BENCH_BATCH=256 BENCH_DTYPE=bf16 BENCH_STEPS=16 BENCH_WARMUP=2
probe && run 900 BENCH_MODEL=transformer BENCH_DTYPE=bf16 BENCH_STEPS=16 BENCH_WARMUP=2
# --- tier 2: the K-step scan loop, same configs -----------------------------
probe && run 1200 BENCH_BATCH=256 BENCH_DTYPE=bf16 BENCH_STEPS=32 BENCH_WARMUP=2 BENCH_MULTISTEP=8
probe && run 1200 BENCH_MODEL=transformer BENCH_DTYPE=bf16 BENCH_STEPS=32 BENCH_WARMUP=2 BENCH_MULTISTEP=8
probe && run 1200 BENCH_BATCH=256 BENCH_DTYPE=bf16 BENCH_STEPS=64 BENCH_WARMUP=2 BENCH_MULTISTEP=32
# (no host-feed multistep tier: run(steps=K) replays an explicit feed
# for all K steps, so BENCH_FEED=host* would credit K steps to 1/K of
# the staging work — bench.py refuses the combination; measuring the
# pipeline under the loop needs an in-graph-reader bench mode first)
# --- tier 2b: sharded weight update on the real mesh (PR 9) ----------------
probe && run 1200 BENCH_SHARDED=1 BENCH_STEPS=32 BENCH_WARMUP=2
probe && run 1200 BENCH_SHARDED=1 BENCH_STEPS=32 BENCH_WARMUP=2 BENCH_SHARDED_DIM=1024
# --- tier 2c: pipelined dispatch (PR 10) — the host/device overlap this
# sweep finally measures on hardware where host and device are separate:
# open-loop serving p50/p99 serial-vs-pipelined at fixed load, and
# steps/s serial-vs-prefetch on a host-io-bound trainer (wide records,
# narrow model; the H2D is the cost prefetch hides)
probe && run 1200 BENCH_PIPELINE=1
probe && run 1200 BENCH_PIPELINE=1 BENCH_PIPELINE_FEAT=8192 BENCH_PIPELINE_BATCH=64
probe && run 1200 BENCH_PIPELINE=1 BENCH_PIPELINE_K=8 BENCH_PIPELINE_RECORDS=64
# --- tier 2d: tensor-parallel plan (PR 11) — mesh-1 vs tp=2/4 on the real
# chips: steps/s per leg + per-chip param bytes from the plan's memory
# accounting + the fetch-divergence column (gather placement: must be 0.0).
# CPU reference (8 virtual devices, dim-64 Adam MLP): divergence 0.0,
# params ratio 0.26 at tp=4; steps/s CPU-parity (the gather win is memory,
# the compute win needs real ICI).
probe && run 1200 BENCH_TP=1 BENCH_STEPS=32 BENCH_WARMUP=2
probe && run 1200 BENCH_TP=1 BENCH_STEPS=32 BENCH_WARMUP=2 BENCH_TP_DIM=1024
probe && run 1200 BENCH_TP=1 BENCH_STEPS=32 BENCH_WARMUP=2 BENCH_TP_DIM=1024 BENCH_TP_LEGS=1,2
# --- tier 2e: self-driving fleet (PR 14) — the fixed-vs-autoscaled 429
# load step on real chips: new replicas land on DISTINCT devices, so qps
# should scale alongside the 429-rate drop (on the 1-core CPU reference
# only the 429 claim is measurable: fixed tail reject rate sustained,
# autoscaled tail ~0, scale-up ~0.3-0.7s riding the AOT warm start,
# contraction drains to 1 with 0 errors — 2026-08-05).
probe && run 1200 BENCH_FLEET=1 BENCH_FLEET_SECONDS=6 BENCH_FLEET_MAX_REPLICAS=4
# --- tier 3k: kernel floor (PR 13) — fused-vs-unfused per op (+ the
# int8/bf16 serving divergence gate riding the same JSON line), then a
# hardware tile sweep (ptpu_tune kernels records per-(op, shape-bucket,
# device_kind) tiles + the flash crossover into the TuningStore), then
# the SAME leg again so tuned_vs_default is measured on the chip — the
# ">=1.5x on >=2 hot ops" ROADMAP claim banks from these lines, never
# from CPU interpret mode. CPU reference (2026-08-05, tiny dims):
# divergence gates all pass; speedups <1 as expected off-hardware.
probe && run 1800 BENCH_KERNELS=1
if [ "$WEDGED" = 0 ]; then
  echo "=== [tune] ptpu_tune kernels --place tpu" | tee -a $LOG
  if bash "$LOCK" timeout -k 10 2400 python tools/ptpu_tune.py kernels \
       --place tpu --json >/tmp/ptpu_tune_kernels.out 2>>$LOG; then
    printf -- '- %s `ptpu_tune kernels --place tpu`\n  `%s`\n' \
      "$(date -u +%FT%TZ)" "$(tail -1 /tmp/ptpu_tune_kernels.out)" \
      >> BENCH_LOG.md
  else
    echo "- $(date -u +%FT%TZ) FAILED: ptpu_tune kernels (see $LOG)" \
      >> BENCH_LOG.md
  fi
  bank
fi
probe && run 1800 BENCH_KERNELS=1
# --- tier 2f: continuous-batched decode (PR 16, ARCHITECTURE.md §27) —
# open-loop streams admitted/retired at iteration boundaries vs the same
# streams decoded one at a time. Headline = continuous tokens/sec; the
# line also carries speedup_vs_serial, mean_slot_occupancy and
# divergence_vs_solo (the leg HARD-FAILS on any nonzero divergence, so a
# banked line is a banked bit-exactness proof). CPU reference
# (2026-08-06, tiny dims): ~2x vs serial at occupancy ~1.5, divergence 0.
probe && run 1200 BENCH_DECODE=1 BENCH_DECODE_STREAMS=64 BENCH_DECODE_SLOTS=8
probe && run 1200 BENCH_DECODE=1 BENCH_DECODE_STREAMS=96 BENCH_DECODE_SLOTS=16 BENCH_DECODE_TOKENS=48
# --- tier 3: big compile LAST — one unrolled TPU line (K copies of the step)
probe && run 2400 BENCH_BATCH=256 BENCH_DTYPE=bf16 BENCH_STEPS=32 BENCH_WARMUP=2 BENCH_MULTISTEP=8 FLAGS_multistep_unroll=1
bank
# r5's queue never got a healthy window (wedged all round): if this
# window is still alive, run it too — its remat/flash-tune items are
# still unmeasured and it probes/banks/exits on its own.
[ "$WEDGED" = 0 ] && bash tools/perf_sweep_r5.sh
echo "=== r6 sweep done (wedged=$WEDGED) ===" | tee -a $LOG
exit $WEDGED
