#!/bin/bash
# DEPRECATED SHIM (PR 19): the r6 sweep queue now lives as data in
# paddle_tpu/benchd/tiers.py (SWEEP_TIERS — same tiers, same
# cheapest-first order, same budgets) and the probe/lock/drain/bank
# protocol in paddle_tpu/benchd/daemon.py.  This script remains only
# because tools/NEXT_SWEEP and the probe loop name it; it execs one
# `ptpu_bench run` window, which drains the queued tiers with per-tier
# done markers (an interrupted sweep resumes — something the shell
# version never did) and exits nonzero when the window wedged so the
# probe loop leaves the sweep queued.  New rounds: re-queue with
# `tools/ptpu_bench.py reset-queue`, not by editing this file.
set -u
cd "$(dirname "$0")/.."
exec python tools/ptpu_bench.py run --git-bank "$@"
