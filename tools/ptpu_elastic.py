#!/usr/bin/env python
"""ptpu_elastic — launch, kill and replace elastic training workers.

The operational front-end of paddle_tpu.resilience.cluster
(ARCHITECTURE.md §19): spawns a cohort of worker processes, runs the
ClusterCoordinator over them (heartbeat monitoring, fence/rollback/
reshard on host death, grow on replacement join), and optionally
replaces dead workers so the mesh grows back.

    # 2 workers, built-in demo MLP, kill worker 1 at step 10 via the
    # fault registry, spawn a replacement once the cohort rescales:
    python tools/ptpu_elastic.py launch --cluster-dir /tmp/el \
        --workers 2 --steps 40 --demo --host-devices 4 --total-devices 4 \
        --fault-worker 1 --fault-plan host_death@10 --replace

    # the same binary is the demo worker entry point (spawned per
    # worker by `launch --demo`):
    python tools/ptpu_elastic.py worker --cluster-dir /tmp/el \
        --worker-id w0 --steps 40

Custom trainers: point --worker-cmd at any script that constructs an
`ElasticWorker` (see the demo_build in this file for the build_fn
shape); the launcher hands it PTPU_CLUSTER_DIR / PTPU_WORKER_ID /
PTPU_ELASTIC_STEPS via env.

Exit codes: 0 = the cohort finished training; 1 = ClusterAborted (the
merged diagnostic bundle path is printed); 2 = usage error.
"""
import argparse
import json
import os
import subprocess
import sys
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


# ------------------------------------------------------------ demo model --
def demo_build(layout):
    """The built-in demo trainer: a deterministic feed-fed MLP (Adam +
    dropout, so the snapshot seed cursor is load-bearing). Batch 8 —
    divisible across any dp size the demo meshes use."""
    import numpy as np
    import paddle_tpu as fluid
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 11
    startup.random_seed = 11
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[6], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=16, act="tanh")
        h = fluid.layers.dropout(h, dropout_prob=0.1)
        p = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.mean(
            x=fluid.layers.square_error_cost(input=p, label=y))
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    rng = np.random.RandomState(5)
    data = [rng.rand(8, 6).astype("float32") for _ in range(32)]

    def feed_fn(i):
        xb = data[i % len(data)]
        return {"x": xb, "y": xb[:, :1].copy()}

    del layout  # the demo trains the same program at every mesh shape
    return {"main": main, "startup": startup, "loss": loss,
            "feed_fn": feed_fn}


def cmd_worker(args):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from paddle_tpu.resilience.cluster import ClusterAborted, ElasticWorker
    worker = ElasticWorker(
        args.cluster_dir, args.worker_id, demo_build,
        checkpoint_every=args.checkpoint_every,
        watchdog_timeout=args.watchdog_timeout,
        sharded_weight_update=args.sharded_weight_update,
        step_delay=args.step_delay,
        metrics_port=args.metrics_port,
        metrics_host=args.metrics_host,
        sentinel=bool(args.sentinel) or None,
        sdc=bool(args.sdc_every) or None,
        sdc_every=args.sdc_every or 64)
    try:
        out = worker.run(args.steps)
    except ClusterAborted as e:
        print("worker %s: %s" % (args.worker_id, e), file=sys.stderr)
        return 1
    print("worker %s finished: %s" % (args.worker_id, out))
    return 0


def cmd_status(args):
    """The fleet gauge table (ARCHITECTURE.md §24): every worker's
    heartbeat-derived status — step cursor, generation acked, beat age,
    steps behind the cohort's front-runner — plus the current plan.
    Exactly the gauges the observability registry exports as
    `ptpu_cluster_worker_*` when a worker serves /metrics."""
    from paddle_tpu.resilience import heartbeat as hb
    from paddle_tpu.resilience.cluster import read_plan
    # scale the staleness window to the fleet's own published beat
    # cadence (heartbeats carry `interval`) — but an operator's
    # EXPLICIT --heartbeat-timeout always wins, tighter or looser
    # (default None = auto)
    if args.heartbeat_timeout is not None:
        timeout = args.heartbeat_timeout
    else:
        intervals = [float(b.get("interval", 0) or 0) for b in
                     hb.read_heartbeats(args.cluster_dir).values()]
        timeout = max([3.0] + [3.0 * i for i in intervals])
    mon = hb.HeartbeatMonitor(args.cluster_dir, timeout=timeout)
    # ONE derivation, shared with the registry's cluster collector
    # (HeartbeatMonitor.fleet_view) — this table and the exported
    # ptpu_cluster_worker_* gauges can never disagree
    rows = mon.fleet_view()
    for r in rows:
        r["beat_age_s"] = round(r["beat_age_s"], 3)
    plan = read_plan(args.cluster_dir)
    quarantine = (plan or {}).get("quarantine") or {}
    if args.json:
        print(json.dumps({
            "plan": None if plan is None else {
                "gen": plan.get("gen"), "phase": plan.get("phase"),
                "num_workers": plan.get("num_workers"),
                "restore_step": plan.get("restore_step"),
                "quarantine": quarantine},
            "workers": rows}, indent=1, sort_keys=True))
        return 0
    if plan is not None:
        print("plan: gen %s phase %s world=%d restore_step=%s%s"
              % (plan.get("gen"), plan.get("phase"),
                 plan.get("num_workers"), plan.get("restore_step"),
                 " quarantine=%s" % json.dumps(quarantine,
                                               sort_keys=True)
                 if quarantine else ""))
    else:
        print("plan: none published yet")
    if not rows:
        print("no heartbeats under %s" % args.cluster_dir)
        return 0
    hdr = "%-8s %-8s %-6s %6s %7s %5s %6s %9s %8s %7s %7s %6s" % (
        "WORKER", "STATUS", "ALIVE", "STEP", "BEHIND", "GEN",
        "ACKED", "BEAT_AGE", "METRICS", "LOSS_Z", "SPIKES", "QUAR")
    print(hdr)
    for r in rows:
        sent = r.get("sentinel") or {}
        z = sent.get("z")
        qdevs = quarantine.get(r["worker"]) or []
        print("%-8s %-8s %-6s %6s %7s %5d %6d %7.2fs %8s %7s %7s %6s"
              % (r["worker"], r["status"], r["alive"], r["step"],
                 "-" if r["steps_behind"] is None else r["steps_behind"],
                 r["gen"], r["gen_acked"], r["beat_age_s"],
                 r["metrics_port"] or "-",
                 "-" if z is None else "%.1f" % z,
                 sent.get("spikes", "-") if sent else "-",
                 ",".join(str(d) for d in qdevs) if qdevs else "-"))
        # a faulted worker's WHY, when it escalated one (the sentinel/
        # canary message is the operator's first clue)
        if r.get("fault") and r.get("status") == "fault":
            extra = ""
            if r.get("sdc_device") is not None:
                extra = " [sdc_device=%s]" % r["sdc_device"]
            print("  `- fault: %.100s%s" % (r["fault"], extra))
    return 0


# -------------------------------------------------------------- launcher --
class _WorkerPool(object):
    """Child-process bookkeeping: spawn, kill, replace."""

    def __init__(self, args):
        self.args = args
        self.procs = {}   # worker_id -> Popen
        self._next = 0
        self._lock = threading.Lock()

    def _worker_env(self, worker_id, with_fault, metrics_port=None):
        env = dict(os.environ)
        env["PTPU_CLUSTER_DIR"] = self.args.cluster_dir
        env["PTPU_WORKER_ID"] = worker_id
        env["PTPU_ELASTIC_STEPS"] = str(self.args.steps)
        if metrics_port is not None:
            # custom --worker-cmd workers read this env default; the
            # built-in worker also gets the explicit flag below
            env["PTPU_METRICS_PORT"] = str(metrics_port)
        else:
            env.pop("PTPU_METRICS_PORT", None)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        if self.args.host_devices:
            env["XLA_FLAGS"] = (
                "--xla_force_host_platform_device_count=%d"
                % self.args.host_devices)
        if with_fault and self.args.fault_plan:
            env["PTPU_FAULT_PLAN"] = self.args.fault_plan
        else:
            env.pop("PTPU_FAULT_PLAN", None)
        return env

    def spawn(self, with_fault=False):
        with self._lock:
            idx = self._next
            worker_id = "w%d" % idx
            self._next += 1
        metrics_port = None
        if getattr(self.args, "metrics_port_base", None):
            metrics_port = int(self.args.metrics_port_base) + idx
        if self.args.worker_cmd:
            cmd = self.args.worker_cmd.split() + [
                "--cluster-dir", self.args.cluster_dir,
                "--worker-id", worker_id, "--steps", str(self.args.steps)]
        else:
            cmd = [sys.executable, os.path.abspath(__file__), "worker",
                   "--cluster-dir", self.args.cluster_dir,
                   "--worker-id", worker_id,
                   "--steps", str(self.args.steps),
                   "--checkpoint-every", str(self.args.checkpoint_every)]
            if self.args.watchdog_timeout:
                cmd += ["--watchdog-timeout",
                        str(self.args.watchdog_timeout)]
            if self.args.sharded_weight_update:
                cmd += ["--sharded-weight-update"]
            if self.args.step_delay:
                cmd += ["--step-delay", str(self.args.step_delay)]
            if metrics_port is not None:
                cmd += ["--metrics-port", str(metrics_port)]
            if getattr(self.args, "sentinel", False):
                cmd += ["--sentinel"]
            if getattr(self.args, "sdc_every", 0):
                cmd += ["--sdc-every", str(self.args.sdc_every)]
        proc = subprocess.Popen(cmd,
                                env=self._worker_env(
                                    worker_id, with_fault,
                                    metrics_port=metrics_port))
        self.procs[worker_id] = proc
        # reap immediately on exit: a SIGKILL'd worker must not linger
        # as a zombie pid the heartbeat monitor reads as alive
        threading.Thread(target=proc.wait, daemon=True).start()
        print("[ptpu_elastic] spawned %s (pid %d%s)"
              % (worker_id, proc.pid,
                 ", fault plan armed" if with_fault
                 and self.args.fault_plan else ""))
        return worker_id

    def kill_all(self):
        for wid, p in self.procs.items():
            if p.poll() is None:
                p.kill()
        for p in self.procs.values():
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001 — shutdown best-effort
                pass


def cmd_launch(args):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from paddle_tpu.resilience.cluster import (ClusterAborted,
                                               ClusterCoordinator)
    os.makedirs(args.cluster_dir, exist_ok=True)
    pool = _WorkerPool(args)
    replaced = {"n": 0}

    def on_event(ev):
        # the "replace a dead host" operator action, automated: once the
        # cohort has rescaled around a death, spawn a fresh worker — the
        # coordinator grows the mesh back at a step barrier
        if args.replace and ev.get("event") == "rescale" \
                and replaced["n"] < args.max_replacements:
            replaced["n"] += 1
            pool.spawn(with_fault=False)

    coord = ClusterCoordinator(
        args.cluster_dir, num_workers=args.workers,
        heartbeat_timeout=args.heartbeat_timeout,
        total_device_count=args.total_devices,
        local_device_count=args.local_devices,
        max_rescales=args.max_rescales,
        on_event=on_event)
    for i in range(args.workers):
        pool.spawn(with_fault=(i == args.fault_worker))
    try:
        summary = coord.run(deadline=args.deadline)
    except ClusterAborted as e:
        print("[ptpu_elastic] ABORTED: %s" % e, file=sys.stderr)
        if e.bundle:
            print("[ptpu_elastic] merged bundle: %s" % e.bundle,
                  file=sys.stderr)
        return 1
    finally:
        pool.kill_all()
    print("[ptpu_elastic] done: %s" % json.dumps(
        {"gen": summary["gen"], "steps": summary["steps"],
         "rescales": coord.rescales,
         "events": [e["event"] for e in summary["events"]]}))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="ptpu_elastic",
                                 description=__doc__.split("\n")[0])
    sub = ap.add_subparsers(dest="cmd")

    lp = sub.add_parser("launch", help="spawn a cohort + coordinator")
    lp.add_argument("--cluster-dir", required=True)
    lp.add_argument("--workers", type=int, default=2)
    lp.add_argument("--steps", type=int, default=40)
    lp.add_argument("--demo", action="store_true",
                    help="use the built-in demo MLP worker (default "
                         "when --worker-cmd is not given)")
    lp.add_argument("--worker-cmd", default=None,
                    help="custom worker command (gets --cluster-dir/"
                         "--worker-id/--steps appended)")
    lp.add_argument("--host-devices", type=int, default=None,
                    help="XLA virtual CPU devices per worker process")
    lp.add_argument("--total-devices", type=int, default=None,
                    help="fixed cluster chip budget re-split across the "
                         "live cohort (shrink => each survivor's mesh "
                         "grows)")
    lp.add_argument("--local-devices", type=int, default=None,
                    help="fixed local mesh size per worker")
    lp.add_argument("--checkpoint-every", type=int, default=4)
    lp.add_argument("--watchdog-timeout", type=float, default=None)
    lp.add_argument("--sharded-weight-update", action="store_true")
    lp.add_argument("--step-delay", type=float, default=0.0,
                    help="demo-worker pacing: sleep per step (gives a "
                         "replacement worker time to join mid-run)")
    lp.add_argument("--heartbeat-timeout", type=float, default=3.0)
    lp.add_argument("--max-rescales", type=int, default=8)
    lp.add_argument("--fault-plan", default=None,
                    help="PTPU_FAULT_PLAN spec armed in ONE worker "
                         "(e.g. host_death@10)")
    lp.add_argument("--fault-worker", type=int, default=-1,
                    help="index of the worker that gets --fault-plan")
    lp.add_argument("--replace", action="store_true",
                    help="spawn a replacement worker after each rescale")
    lp.add_argument("--max-replacements", type=int, default=1)
    lp.add_argument("--deadline", type=float, default=None,
                    help="abort the whole run after this many seconds")
    lp.add_argument("--metrics-port-base", type=int, default=None,
                    help="serve each worker's /metrics (observability "
                         "registry incl. fleet gauges) on base+index")
    lp.add_argument("--sentinel", action="store_true",
                    help="arm the training-health sentinel in every "
                         "demo worker (loss-spike rollback_skip_data, "
                         "divergence detection)")
    lp.add_argument("--sdc-every", type=int, default=0,
                    help="run the SDC canary every N steps in every "
                         "demo worker (0 = off); a conviction "
                         "quarantines the device")
    lp.set_defaults(fn=cmd_launch)

    sp = sub.add_parser("status", help="fleet gauge table from "
                                       "heartbeats")
    sp.add_argument("--cluster-dir", required=True)
    sp.add_argument("--heartbeat-timeout", type=float, default=None,
                    help="staleness window (default: 3x the fleet's "
                         "published beat interval, floor 3s)")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_status)

    wp = sub.add_parser("worker", help="built-in demo worker")
    wp.add_argument("--cluster-dir",
                    default=os.environ.get("PTPU_CLUSTER_DIR"))
    wp.add_argument("--worker-id",
                    default=os.environ.get("PTPU_WORKER_ID"))
    wp.add_argument("--steps", type=int,
                    default=int(os.environ.get("PTPU_ELASTIC_STEPS",
                                               "40")))
    wp.add_argument("--checkpoint-every", type=int, default=4)
    wp.add_argument("--watchdog-timeout", type=float, default=None)
    wp.add_argument("--sharded-weight-update", action="store_true")
    wp.add_argument("--step-delay", type=float, default=0.0)
    wp.add_argument("--metrics-port", type=int,
                    default=(int(os.environ["PTPU_METRICS_PORT"])
                             if os.environ.get("PTPU_METRICS_PORT")
                             else None),
                    help="serve the observability registry's /metrics "
                         "on this port (0 = pick free)")
    wp.add_argument("--metrics-host", default="127.0.0.1",
                    help="bind address for /metrics (0.0.0.0 for a "
                         "remote scraper; the heartbeat's host field "
                         "names the machine)")
    wp.add_argument("--sentinel", action="store_true",
                    help="arm the training-health sentinel")
    wp.add_argument("--sdc-every", type=int, default=0,
                    help="SDC canary cadence in steps (0 = off)")
    wp.set_defaults(fn=cmd_worker)

    args = ap.parse_args(argv)
    if args.cmd is None:
        ap.print_help()
        return 2
    if args.cmd == "worker" and (not args.cluster_dir
                                 or not args.worker_id):
        ap.error("worker needs --cluster-dir and --worker-id "
                 "(or PTPU_CLUSTER_DIR / PTPU_WORKER_ID)")
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
