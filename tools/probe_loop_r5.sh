#!/bin/bash
# Round-5 probe loop. Probes the tunnel every ~20 min under the exclusive
# client lock; on the FIRST healthy probe, runs the sweep script named in
# tools/NEXT_SWEEP (re-read at fire time so the queued sweep can be
# upgraded mid-round without restarting the loop), then RESUMES probing —
# NEXT_SWEEP may be updated again after a window closes. Single instance
# via its own lock. Log: /tmp/probe_loop_r5.log
exec 9>/tmp/probe_loop_r5.lock
flock -n 9 || { echo "probe_loop_r5 already running"; exit 0; }
cd /root/repo
LOG=/tmp/probe_loop_r5.log
# Health = a NON-CPU device actually initialized; jax's silent CPU
# fallback (tunnel down but fast-failing) must read as DOWN, not healthy.
PROBE='import jax,sys; sys.exit(0 if any(d.platform!="cpu" for d in jax.devices()) else 1)'
for i in $(seq 1 32); do
  if bash tools/tpu_lock.sh timeout 120 python -c "$PROBE" >/dev/null 2>&1; then
    SWEEP=$(head -1 tools/NEXT_SWEEP 2>/dev/null)
    if [ -n "$SWEEP" ] && [ -f "$SWEEP" ]; then
      echo "$(date -u +%FT%TZ) RECOVERED on probe $i — firing $SWEEP" >> $LOG
      if bash "$SWEEP" >> $LOG 2>&1; then
        # consume only after a successful run; a sweep that aborted
        # (lock contention, tunnel died mid-run) stays queued and
        # refires on the next healthy probe
        : > tools/NEXT_SWEEP
        echo "$(date -u +%FT%TZ) sweep $SWEEP finished; consumed" >> $LOG
      else
        echo "$(date -u +%FT%TZ) sweep $SWEEP failed (rc=$?); left queued" >> $LOG
        sleep 1200
      fi
    else
      echo "$(date -u +%FT%TZ) probe $i healthy; no sweep queued" >> $LOG
      sleep 1200
    fi
  else
    echo "$(date -u +%FT%TZ) probe $i rc!=0 (tunnel down or lock busy)" >> $LOG
    sleep 1200
  fi
done
echo "$(date -u +%FT%TZ) probe loop exhausted (32 probes)" >> $LOG
