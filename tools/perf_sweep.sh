#!/bin/bash
# Round-start perf sweep on the REAL chip. Run FIRST THING in a round while
# the axon tunnel is fresh (it can wedge permanently on concurrent clients
# or giant remote compiles — see ARCHITECTURE.md / memory notes):
#   bash tools/perf_sweep.sh
# STRICT CHEAPEST-FIRST ORDER (r3 verdict weak #4): the safe headline config
# (bf16 batch 256 device feed) runs first and is git-committed the moment it
# succeeds; escalating configs (batch 512/1024, layout probe's multi-compile,
# 2k-seq transformer) only run after the bank is safe, each gated on a fresh
# tunnel probe so one wedge can't take later cheap configs down with it.
# Best known config (round 2): bf16 batch 256 device feed = 2205 img/s
# (~14% MFU of a v5e's 197 bf16 TFLOPs). Targets worth testing for >25% MFU:
# batch 512/1024 (+BENCH_REMAT=1), NHWC, XLA latency-hiding flags.
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/perf_sweep.log
: > $LOG
WEDGED=0
LOCK="tools/tpu_lock.sh"  # exclusive-tunnel flock (round-4 re-wedge); we
                          # cd'd to the repo root above
tunnel_ok() {  # raw 120s device probe, no WEDGED short-circuit
  bash "$LOCK" timeout 120 python -c "import jax; print(jax.devices())"
}
probe() {  # never start a compile against a wedged tunnel
  [ "$WEDGED" = 1 ] && return 1
  tunnel_ok
  local rc=$?
  [ $rc -eq 0 ] && return 0
  if [ $rc -eq 75 ]; then  # tpu_lock timeout: busy, NOT a wedge diagnosis
    echo "TPU LOCK BUSY - skipping remaining configs (not a wedge)" | tee -a $LOG
    echo "- $(date -u +%FT%TZ) sweep stopped: tpu_lock busy (rc=75)" >> BENCH_LOG.md
  else
    echo "TUNNEL WEDGED - skipping remaining configs" | tee -a $LOG
    echo "- $(date -u +%FT%TZ) tunnel probe FAILED mid-sweep" >> BENCH_LOG.md
  fi
  WEDGED=1
  return 1
}
bank() {  # commit the log so a later wedge cannot erase banked numbers
  # pathspec-limited: never sweeps unrelated staged work into the bank
  git commit -q -m "perf sweep: bank measured bench lines" \
    -- BENCH_LOG.md 2>/dev/null || true
}
run() {
  [ "$WEDGED" = 1 ] && { echo "skip (wedged): $*" | tee -a $LOG; return; }
  echo "=== $*" | tee -a $LOG
  local line rc
  bash "$LOCK" env "$@" BENCH_DEVICE_TIMEOUT=300 timeout -k 10 900 \
    python bench.py >/tmp/bench_run.out 2>/dev/null
  rc=$?
  line=$(tail -1 /tmp/bench_run.out)
  if [ $rc -eq 75 ]; then  # lock busy: not a bench failure, not a wedge
    echo "TPU LOCK BUSY - stopping sweep (not a wedge)" | tee -a $LOG
    echo "- $(date -u +%FT%TZ) sweep stopped mid-run: tpu_lock busy (rc=75)" >> BENCH_LOG.md
    WEDGED=1
    return
  fi
  echo "$line" | tee -a $LOG
  # persist every successful measurement the moment it exists (r2 verdict
  # weak #1: a later wedge must not erase the round's perf story)
  case "$line" in
    *'"error"'*|"")
      echo "- $(date -u +%FT%TZ) FAILED: $*" >> BENCH_LOG.md
      # a device-init timeout means the tunnel is gone; an EMPTY line is
      # ambiguous (timeout-killed mid-compile OR an ordinary crash with
      # stderr discarded) — re-probe to tell the two apart before
      # writing off the rest of the sweep
      case "$line" in
        *"device init"*) WEDGED=1 ;;
        "") tunnel_ok || WEDGED=1 ;;
      esac ;;
    *) printf -- '- %s `%s`\n  `%s`\n' "$(date -u +%FT%TZ)" "$*" "$line" \
         >> BENCH_LOG.md
       bank ;;
  esac
}
probe || exit 1
# ---- tier 1: the safe headline config, banked immediately --------------
run BENCH_BATCH=256 BENCH_DTYPE=bf16
probe && run BENCH_BATCH=256 BENCH_DTYPE=bf16 BENCH_FEED=host BENCH_STEPS=10 BENCH_WARMUP=3
# ---- tier 2: cheap single-compile variants -----------------------------
probe && run BENCH_BATCH=256 BENCH_DTYPE=bf16 FLAGS_conv_layout=NHWC
probe && run BENCH_BATCH=256 BENCH_DTYPE=bf16 \
  XLA_FLAGS="${XLA_FLAGS:-} --xla_tpu_enable_latency_hiding_scheduler=true"
probe && run BENCH_MODEL=transformer BENCH_BATCH=32 BENCH_SEQ=256
probe && run BENCH_MODEL=transformer BENCH_BATCH=32 BENCH_SEQ=256 BENCH_FUSED_ATTN=0
# ---- tier 3: multi-compile probe + pallas microbench -------------------
if probe; then
  bash "$LOCK" timeout 600 python tools/layout_probe.py 2>/dev/null | tee -a $LOG
  echo "=== pallas microbench" | tee -a $LOG
  bash "$LOCK" timeout 900 python tools/pallas_microbench.py 2>/dev/null | tee -a $LOG | \
    while read -r line; do
      printf -- '- %s microbench `%s`\n' "$(date -u +%FT%TZ)" "$line" >> BENCH_LOG.md
    done
  [ "${PIPESTATUS[0]:-0}" = 0 ] || \
    echo "- $(date -u +%FT%TZ) FAILED: pallas_microbench (rc)" >> BENCH_LOG.md
  bank
fi
# ---- tier 4: big compiles LAST (the r2 wedge was a batch-512 compile) --
probe && run BENCH_BATCH=512 BENCH_DTYPE=bf16 BENCH_STEPS=10 BENCH_WARMUP=3
probe && run BENCH_BATCH=512 BENCH_DTYPE=bf16 BENCH_STEPS=10 BENCH_WARMUP=3 BENCH_REMAT=1
probe && run BENCH_BATCH=1024 BENCH_DTYPE=bf16 BENCH_STEPS=10 BENCH_WARMUP=3 BENCH_REMAT=1
# long-context: the flash path's O(T) memory is the point — dense would
# materialize [T,T] attention at 2k tokens
probe && run BENCH_MODEL=transformer BENCH_BATCH=4 BENCH_SEQ=2048 BENCH_STEPS=5 BENCH_WARMUP=2
bank
echo "=== sweep done (wedged=$WEDGED) ===" | tee -a $LOG
