#!/bin/bash
# Round-start perf sweep on the REAL chip. Run FIRST THING in a round while
# the axon tunnel is fresh (it can wedge permanently on concurrent clients
# or giant remote compiles — see ARCHITECTURE.md / memory notes):
#   bash tools/perf_sweep.sh
# Probes layout, batch, remat, and feed-mode configs; one JSON line each in
# /tmp/perf_sweep.log. Best known config (round 2): bf16 batch 256 device
# feed = 2205 img/s (~14% MFU of a v5e's 197 bf16 TFLOPs). Targets worth
# testing for >25% MFU: batch 512/1024 (+BENCH_REMAT=1), NHWC (see
# layout_probe), XLA latency-hiding flags.
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/perf_sweep.log
: > $LOG
probe() {  # never start a sweep against a wedged tunnel
  timeout 120 python -c "import jax; print(jax.devices())" || {
    echo "TUNNEL WEDGED - aborting sweep" | tee -a $LOG
    echo "- $(date -u +%FT%TZ) tunnel probe FAILED (sweep aborted)" >> BENCH_LOG.md
    exit 1; }
}
run() {
  echo "=== $*" | tee -a $LOG
  local line
  line=$(env "$@" BENCH_DEVICE_TIMEOUT=300 timeout 900 python bench.py \
         2>/dev/null | tail -1)
  echo "$line" | tee -a $LOG
  # persist every successful measurement the moment it exists (r2 verdict
  # weak #1: a later wedge must not erase the round's perf story)
  case "$line" in
    *'"error"'*|"") echo "- $(date -u +%FT%TZ) FAILED: $*" >> BENCH_LOG.md ;;
    *) printf -- '- %s `%s`\n  `%s`\n' "$(date -u +%FT%TZ)" "$*" "$line" \
         >> BENCH_LOG.md ;;
  esac
}
probe
timeout 600 python tools/layout_probe.py 2>/dev/null | tee -a $LOG
run BENCH_BATCH=256 BENCH_DTYPE=bf16
run BENCH_BATCH=256 BENCH_DTYPE=bf16 FLAGS_conv_layout=NHWC
run BENCH_BATCH=512 BENCH_DTYPE=bf16 BENCH_STEPS=10 BENCH_WARMUP=3
run BENCH_BATCH=512 BENCH_DTYPE=bf16 BENCH_STEPS=10 BENCH_WARMUP=3 BENCH_REMAT=1
run BENCH_BATCH=1024 BENCH_DTYPE=bf16 BENCH_STEPS=10 BENCH_WARMUP=3 BENCH_REMAT=1
run BENCH_BATCH=256 BENCH_DTYPE=bf16 BENCH_FEED=host BENCH_STEPS=10 BENCH_WARMUP=3
run BENCH_BATCH=256 BENCH_DTYPE=bf16 \
  XLA_FLAGS="${XLA_FLAGS:-} --xla_tpu_enable_latency_hiding_scheduler=true"
run BENCH_MODEL=transformer BENCH_BATCH=32 BENCH_SEQ=256
run BENCH_MODEL=transformer BENCH_BATCH=32 BENCH_SEQ=256 BENCH_FUSED_ATTN=0
# long-context: the flash path's O(T) memory is the point — dense would
# materialize [T,T] attention at 2k tokens
run BENCH_MODEL=transformer BENCH_BATCH=4 BENCH_SEQ=2048 BENCH_STEPS=5 BENCH_WARMUP=2
echo "=== pallas microbench" | tee -a $LOG
timeout 900 python tools/pallas_microbench.py 2>/dev/null | tee -a $LOG | \
  while read -r line; do
    printf -- '- %s microbench `%s`\n' "$(date -u +%FT%TZ)" "$line" >> BENCH_LOG.md
  done
[ "${PIPESTATUS[0]:-0}" = 0 ] || \
  echo "- $(date -u +%FT%TZ) FAILED: pallas_microbench (rc)" >> BENCH_LOG.md
echo "=== sweep done ===" | tee -a $LOG
