#!/bin/bash
# Probe the tunnel every ~20 min; on the FIRST healthy probe, run the r4c
# sweep (which banks+commits each measured line) and exit. Single
# instance via its own lock.
exec 9>/tmp/probe_loop.lock
flock -n 9 || { echo "probe_loop already running"; exit 0; }
cd /root/repo
for i in $(seq 1 14); do
  if bash tools/tpu_lock.sh timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) RECOVERED on probe $i — starting r4c sweep" >> /tmp/probe_loop.log
    bash tools/perf_sweep_r4c.sh >> /tmp/probe_loop.log 2>&1
    exit 0
  fi
  echo "$(date -u +%FT%TZ) probe $i rc=124" >> /tmp/probe_loop.log
  sleep 1200
done
echo "$(date -u +%FT%TZ) probe loop exhausted (14 probes)" >> /tmp/probe_loop.log
