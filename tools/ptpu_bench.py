#!/usr/bin/env python3
"""ptpu_bench — continuous hardware benching (paddle_tpu.benchd).

    tools/ptpu_bench.py run [--store DIR] [--tier NAME] [--probe-timeout S]
                        [--git-bank] [--json]
        One hardware window NOW: probe the device once; when healthy,
        take the client window lock and drain the queued sweep tiers
        cheapest-first (resuming at the first tier without a done
        marker), committing each banked JSON line to the bench store
        and appending BENCH_LOG.md.  This is what perf_sweep_r*.sh
        became: the shims exec it.  Exits nonzero when the window is
        wedged/lock-busy so a probe loop leaves the sweep queued.

    tools/ptpu_bench.py daemon [--store DIR] [--interval S]
                        [--probe-timeout S] [--max-cycles N] [--git-bank]
        The resident loop: probe every --interval seconds, drain on
        each healthy window, publish ptpu_bench_* gauges, until the
        queue is empty (or --max-cycles).

    tools/ptpu_bench.py gate [--store DIR] [--fresh FILE.jsonl]
                        [--json]
        Perf-regression gate.  With --fresh, each line of FILE is a
        bench record gated against the store's last-good baseline for
        its (metric, device_kind, config) key; without it, the store
        self-gates its newest record per key (the CI smoke mode over
        the committed artifacts).  Error placeholders skip, never fail.

    tools/ptpu_bench.py status [--store DIR] [--json]
        The store summarized: the BENCH_r* driver series classified
        (last-good baseline vs probe failures), last-good values per
        key, queued/done sweep tiers, last daemon cycle.

    tools/ptpu_bench.py reset-queue [--store DIR] [--tier NAME]
        Re-queue one tier (or all) for the next window — the new-round
        verb that editing NEXT_SWEEP used to be.

Store/state default to <repo>/bench_store (first open backfills the
committed BENCH_r*.json + BENCH_LOG.md lines).  `gate` and `status`
never dial the tunnel; `run`/`daemon` probe it in a hard-deadlined
subprocess and only ever touch the device from child processes.

Exit codes: 0 ok (gate: no regressions; run: window drained), 1 gate
regression / run window not drained (wedged, down, lock-busy), 2 bad
invocation.
"""
import argparse
import json
import os
import sys

# the CLI process itself never initializes a device backend — probes
# and sweep runs are subprocesses that drop this pin (benchd.probe)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _store_root(args):
    return args.store or os.path.join(_REPO, "bench_store")


def _open_store(args):
    from paddle_tpu.benchd import BenchStore
    return BenchStore(_store_root(args), repo_root=_REPO)


def _tier_list(args):
    from paddle_tpu.benchd import SWEEP_TIERS, tiers as _tiers
    if getattr(args, "tier", None):
        return [_tiers.tier_by_name(args.tier)]
    return list(SWEEP_TIERS)


def cmd_run(args):
    from paddle_tpu.benchd import BenchDaemon
    with BenchDaemon(repo_root=_REPO, state_dir=_store_root(args),
                     tiers=_tier_list(args),
                     probe_timeout_s=args.probe_timeout,
                     git_bank=args.git_bank) as d:
        cycle = d.run_once()
    window = cycle.get("window") or {"state": cycle["probe"]["status"]}
    if args.json:
        print(json.dumps(cycle, indent=1, default=str))
    else:
        print("probe: %s" % cycle["probe"]["status"])
        print("window: %s" % window.get("state"))
        for name in window.get("banked", []):
            print("  banked %s" % name)
        for f in window.get("failed", []):
            print("  FAILED %s: %s" % (f["tier"], f["error"]))
        if window.get("pending_after"):
            print("still queued: %s"
                  % " ".join(window["pending_after"]))
    return 0 if window.get("state") == "drained" else 1


def cmd_daemon(args):
    from paddle_tpu.benchd import BenchDaemon
    with BenchDaemon(repo_root=_REPO, state_dir=_store_root(args),
                     probe_timeout_s=args.probe_timeout,
                     interval_s=args.interval,
                     git_bank=args.git_bank) as d:
        cycle = d.run_forever(max_cycles=args.max_cycles)
    pending = (cycle.get("window") or {}).get("pending_after")
    print("benchd: stopped; pending=%s" % (pending or "none"))
    return 0


def _load_fresh(path):
    from paddle_tpu.benchd import schema
    fresh = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            # accept bare records or store envelopes
            if isinstance(rec, dict) and "record" in rec and "v" in rec:
                env, rec = rec, rec["record"]
            else:
                env = {"record": rec}
            schema.check_record(rec)
            env.setdefault("metric", rec.get("metric"))
            env.setdefault("device_kind", schema.device_kind(rec))
            env.setdefault("digest", schema.config_digest(rec))
            fresh.append(env)
    return fresh


def cmd_gate(args):
    from paddle_tpu.benchd import run_gate
    store = _open_store(args)
    fresh = None
    if args.fresh:
        try:
            fresh = _load_fresh(args.fresh)
        except (OSError, ValueError) as e:
            print("ptpu_bench gate: bad --fresh file: %s" % e,
                  file=sys.stderr)
            return 2
    report = run_gate(store, fresh=fresh)
    if args.json:
        print(json.dumps(report, indent=1, default=str))
    else:
        for v in report["verdicts"]:
            mark = {"regression": "FAIL", "improvement": "GOOD"}.get(
                v["verdict"], "ok")
            print("%-4s %s" % (mark, v["detail"]))
        print("gate: %d regression(s) across %d key(s)"
              % (report["regressions"], len(report["verdicts"])))
    return report["exit_code"]


def cmd_status(args):
    from paddle_tpu.benchd import SweepQueue, is_error
    store = _open_store(args)
    summ = store.summary()
    driver = store.entries(source_prefix="backfill:BENCH_r")
    driver_rows = []
    for env in driver:
        rec = env["record"]
        driver_rows.append({
            "source": env["source"].split(":", 1)[1],
            "class": ("probe-failure" if is_error(rec)
                      else "hardware-baseline"),
            "value": rec.get("value"),
            "error": rec.get("error"),
        })
    good_driver = [r["source"] for r in driver_rows
                   if r["class"] == "hardware-baseline"]
    queue = SweepQueue(os.path.join(_store_root(args), "sweep_state"))
    status_path = os.path.join(_store_root(args), "status.json")
    try:
        with open(status_path) as f:
            daemon_status = json.load(f)
    except (OSError, ValueError):
        daemon_status = None
    out = {
        "store": {"records": summ["records"], "errors": summ["errors"]},
        "driver_series": {"rows": driver_rows,
                          "last_good": good_driver},
        "last_good": {
            "%s @ %s" % k: {
                "value": slot["last_good"]["record"]["value"],
                "source": slot["last_good"]["source"],
            }
            for k, slot in sorted(summ["keys"].items())
            if slot["last_good"] is not None},
        "queue": queue.describe(),
        "daemon": daemon_status,
    }
    if args.json:
        print(json.dumps(out, indent=1, default=str))
        return 0
    print("bench store: %d record(s), %d error placeholder(s)"
          % (summ["records"], summ["errors"]))
    print("driver series (BENCH_r*.json):")
    for row in driver_rows:
        print("  %-16s %-18s %s"
              % (row["source"], row["class"],
                 row["error"] or row["value"]))
    print("last-good baselines:")
    for key, slot in sorted(out["last_good"].items()):
        print("  %-60s %s  (%s)" % (key, slot["value"], slot["source"]))
    q = out["queue"]
    print("sweep queue: %d pending, %d done"
          % (len(q["pending"]), len(q["done"])))
    if daemon_status:
        probe = daemon_status.get("cycle", {}).get("probe", {})
        print("last daemon cycle: probe=%s counts=%s"
              % (probe.get("status"), daemon_status.get("counts")))
    return 0


def cmd_reset_queue(args):
    from paddle_tpu.benchd import SweepQueue
    queue = SweepQueue(os.path.join(_store_root(args), "sweep_state"))
    queue.reset(args.tier)
    print("re-queued: %s" % (args.tier or "all tiers"))
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="ptpu_bench",
        description="continuous hardware benching (paddle_tpu.benchd)")
    p.add_argument("--store", default=None,
                   help="store/state dir (default <repo>/bench_store)")
    sub = p.add_subparsers(dest="cmd", required=True)

    runp = sub.add_parser("run", help="drain one hardware window now")
    runp.add_argument("--tier", default=None,
                      help="run only this tier")
    runp.add_argument("--probe-timeout", type=int, default=120)
    runp.add_argument("--git-bank", action="store_true",
                      help="git-commit BENCH_LOG.md after each banked "
                           "line (the r6 rule)")
    runp.add_argument("--json", action="store_true")
    runp.set_defaults(fn=cmd_run)

    dp = sub.add_parser("daemon", help="resident probe/drain loop")
    dp.add_argument("--interval", type=int, default=1200,
                    help="seconds between probes (default 1200 — the "
                         "probe_loop_r5 cadence)")
    dp.add_argument("--probe-timeout", type=int, default=120)
    dp.add_argument("--max-cycles", type=int, default=None)
    dp.add_argument("--git-bank", action="store_true")
    dp.set_defaults(fn=cmd_daemon)

    gp = sub.add_parser("gate", help="perf-regression gate")
    gp.add_argument("--fresh", default=None,
                    help="JSONL of fresh records to gate (default: "
                         "self-gate the store's newest per key)")
    gp.add_argument("--json", action="store_true")
    gp.set_defaults(fn=cmd_gate)

    sp = sub.add_parser("status", help="store + queue + daemon status")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_status)

    rp = sub.add_parser("reset-queue", help="re-queue tiers")
    rp.add_argument("--tier", default=None)
    rp.set_defaults(fn=cmd_reset_queue)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
