"""Probe: NCHW vs NHWC conv layout cost on the real TPU for a ResNet-50-ish
stack of convs, fwd+bwd. Run standalone: python tools/layout_probe.py"""
import os
import sys
import time
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from paddle_tpu import tpu_guard  # noqa: E402,F401 - mandatory lock guard
from paddle_tpu.core.utils import device_fetch_barrier  # noqa: E402

# The image's sitecustomize pins jax config to "axon,cpu" regardless of the
# env var; honor an explicit JAX_PLATFORMS request (cpu smoke runs must not
# dial the tunnel), same as bench.py/_await().
_want = os.environ.get("JAX_PLATFORMS")
if _want:
    jax.config.update("jax_platforms", _want)
# Loud-failure rule: refuse to emit CPU timings dressed up as TPU data.
tpu_guard.require_accelerator("layout_probe")


def conv_stack(layout):
    dn = (layout, "OIHW" if layout == "NCHW" else "HWIO", layout)

    def apply(params, x):
        for w in params:
            x = lax.conv_general_dilated(
                x, w, window_strides=(1, 1), padding="SAME",
                dimension_numbers=dn)
            x = jnp.maximum(x, 0)
        return jnp.sum(x.astype(jnp.float32))

    return apply


def bench_layout(layout, batch=256, c=256, hw=14, k=3, depth=8, steps=20):
    rng = np.random.RandomState(0)
    if layout == "NCHW":
        x = jnp.asarray(rng.rand(batch, c, hw, hw).astype(np.float32),
                        dtype=jnp.bfloat16)
        ws = [jnp.asarray(rng.randn(c, c, k, k).astype(np.float32) * 0.05,
                          dtype=jnp.bfloat16) for _ in range(depth)]
    else:
        x = jnp.asarray(rng.rand(batch, hw, hw, c).astype(np.float32),
                        dtype=jnp.bfloat16)
        ws = [jnp.asarray(rng.randn(k, k, c, c).astype(np.float32) * 0.05,
                          dtype=jnp.bfloat16) for _ in range(depth)]
    apply = conv_stack(layout)
    grad = jax.jit(jax.grad(apply))
    g = grad(ws, x)
    device_fetch_barrier(g)
    t0 = time.perf_counter()
    for _ in range(steps):
        g = grad(ws, x)
    device_fetch_barrier(g)
    dt = (time.perf_counter() - t0) / steps
    flops = 2 * 3 * depth * batch * hw * hw * c * c * k * k  # fwd+bwd(2x)
    print("%s: %.2f ms/step, %.1f TFLOP/s" % (layout, dt * 1e3,
                                              flops / dt / 1e12))


if __name__ == "__main__":
    for layout in ("NCHW", "NHWC"):
        bench_layout(layout)
