#!/usr/bin/env python3
"""pplint — static verifier for saved paddle_tpu / era-Fluid programs.

Runs the paddle_tpu/analysis pass pipeline (use-before-def, shape/dtype
consistency, unregistered ops, reader placement, feed/fetch carriers)
over a SERIALIZED program, without executing it:

    tools/pplint.py <model-dir>              # save_inference_model /
                                             # save_reference_model dir
    tools/pplint.py <model-dir>/__model__    # a bare desc file
    tools/pplint.py <checkpoint-dir>         # CheckpointManager root:
                                             # lints the program recorded
                                             # in the newest VALID snapshot
    tools/pplint.py <ckpt>/step_100          # one snapshot (its program
                                             # hash-verified before lint)
    tools/pplint.py path --strict            # warnings also fail

Accepted formats (auto-detected from the first bytes):
  * native versioned JSON desc (core/program_desc.py)        -> b'{'
  * round-1 legacy pickle                                    -> b'\\x80'
  * era-wire ProgramDesc protobuf (reference_format.py)      -> anything
    else; the wire-level feed/fetch carrier checks run BEFORE the desc
    is parsed, then the parsed program goes through the full pipeline.

Feed/fetch targets come from __model_meta__.json (native dirs) or the
era feed/fetch plumbing ops (strip_feed_fetch). Exit codes: 0 clean,
1 findings, 2 bad invocation / unreadable model.
"""
import argparse
import json
import os
import sys

# lint must never dial a TPU tunnel / take the exclusive client lock
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _resolve_checkpoint_dir(path):
    """Map a checkpoint layout onto the program desc it records, or None
    when `path` is not a checkpoint. Accepts a checkpoint ROOT (step_<N>
    dirs / LATEST: the newest snapshot whose hash tree verifies wins,
    like CheckpointManager.restore) or one snapshot dir (snapshot.json:
    linted exactly as given — corruption is a hard error here, since the
    user pointed at THIS snapshot). Both paths verify only what the lint
    reads (structure, manifest hash, the program's own sha256) — array
    payloads are ptpu_ckpt verify's job, not GBs of reads for a lint."""
    from paddle_tpu.checkpoint import snapshot as snap
    if os.path.exists(os.path.join(path, snap.SNAPSHOT_FILE)):
        problems = snap.verify_snapshot_light(path)
        if problems:
            raise ValueError("corrupt snapshot %s: %s"
                             % (path, "; ".join(problems)))
        meta = snap.read_snapshot_meta(path)
    elif snap.list_steps(path) or os.path.exists(
            os.path.join(path, snap.LATEST_FILE)):
        # newest-first walk, but only as much hashing as the lint needs:
        # structure + manifest hash + the recorded program's own sha256
        # (verify_snapshot_light) — NOT every array file, which on a real
        # checkpoint is GBs of reads for zero lint value
        meta = None
        for _, cand in reversed(snap.list_steps(path)):
            if snap.verify_snapshot_light(cand):
                continue
            meta, path = snap.read_snapshot_meta(cand), cand
            break
        if meta is None:
            raise ValueError("checkpoint dir %s has no snapshot that "
                             "verifies" % path)
    else:
        return None
    prog = meta.get("program")
    if not prog:
        raise ValueError("snapshot %s records no program (legacy "
                         "io.save_checkpoint layout)" % path)
    return os.path.join(path, prog["file"])


def load_program(path, model_filename=None, allow_pickle=False):
    """-> (program, feed_names, fetch_names, wire_diagnostics)."""
    import paddle_tpu as fluid
    from paddle_tpu import reference_format as rf
    from paddle_tpu.analysis import check_wire_carriers

    meta_feeds = meta_fetches = None
    if os.path.isdir(path):
        ckpt_desc = _resolve_checkpoint_dir(path)
        if ckpt_desc is not None:
            # training-checkpoint program: no feed/fetch contract is
            # recorded; analysis falls back to the is_data convention
            path = ckpt_desc
        else:
            meta_path = os.path.join(path, "__model_meta__.json")
            if os.path.exists(meta_path):
                with open(meta_path) as f:
                    meta = json.load(f)
                meta_feeds, meta_fetches = (meta.get("feed"),
                                            meta.get("fetch"))
            path = os.path.join(path, model_filename or "__model__")
    with open(path, "rb") as f:
        raw = f.read()

    if raw[:1] == b"{":  # native versioned JSON desc
        program = fluid.Program.parse_from_string(raw)
        return program, meta_feeds, meta_fetches, []
    if raw[:1] == b"\x80":  # round-1 legacy pickle artifact
        # unpickling EXECUTES code from the file — never do that by
        # default in a lint tool whose whole job is inspecting artifacts
        # of unknown provenance
        if not allow_pickle:
            raise ValueError(
                "legacy pickle desc: unpickling executes code from the "
                "file; pass --allow-pickle only for artifacts you trust")
        import pickle
        program = pickle.loads(raw)
        return program, meta_feeds, meta_fetches, []
    # era-wire protobuf: carrier checks at the WIRE level first, then
    # parse (which strips the feed/fetch plumbing) and the layout adapter.
    # A malformation that also breaks parsing must still REPORT the wire
    # diagnostics that explain it, not vanish behind a load error.
    blocks = rf._parse_blocks(raw)
    wire_diags = check_wire_carriers(blocks)
    try:
        program = rf.parse_program_desc(blocks)
        feeds, fetches = rf.strip_feed_fetch(blocks)
        rf.adapt_sequence_layout(program, feeds)
    except Exception:
        if wire_diags:
            return None, None, None, wire_diags
        raise
    return program, meta_feeds or feeds, meta_fetches or fetches, wire_diags


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="pplint", description="static verifier for saved programs")
    ap.add_argument("path", help="model directory or program desc file")
    ap.add_argument("--model-filename", default=None,
                    help="desc filename inside a model dir "
                         "(default __model__)")
    ap.add_argument("--steps", type=int, default=1,
                    help="validate for Executor.run(steps=K) semantics")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on warnings too")
    ap.add_argument("--no-callstack", action="store_true",
                    help="omit op creation stacks from output")
    ap.add_argument("--allow-pickle", action="store_true",
                    help="permit loading round-1 legacy pickle descs "
                         "(unpickling executes code — trusted files only)")
    args = ap.parse_args(argv)

    try:
        program, feeds, fetches, wire_diags = load_program(
            args.path, args.model_filename,
            allow_pickle=args.allow_pickle)
    except Exception as e:
        print("pplint: cannot load %s: %s" % (args.path, e),
              file=sys.stderr)
        return 2

    from paddle_tpu import analysis
    if program is None:
        # wire carrier errors AND an unparseable desc: the diagnostics
        # are the explanation — report them instead of a bare load error
        result = analysis.AnalysisResult(wire_diags)
    else:
        result = analysis.analyze(program, feed_names=feeds,
                                  fetch_names=fetches, steps=args.steps)
        result.diagnostics[:0] = wire_diags  # wire findings lead, in order

    for d in result:
        print(d.format(with_callstack=not args.no_callstack))
    print("pplint: %d error(s), %d warning(s) in %s"
          % (len(result.errors), len(result.warnings), args.path))
    if result.errors or (args.strict and result.warnings):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
