#!/usr/bin/env python3
"""pplint — static verifier for saved paddle_tpu / era-Fluid programs.

Runs the paddle_tpu/analysis pass pipeline (use-before-def, shape/dtype
consistency, unregistered ops, reader placement, feed/fetch carriers)
over a SERIALIZED program, without executing it — plus, on request, the
deployment tier (row-independence, sharding-consistency, dtype-flow,
decode-invariants, donation-safety) under a deployment context:

    tools/pplint.py <model-dir>              # save_inference_model /
                                             # save_reference_model dir
    tools/pplint.py <model-dir>/__model__    # a bare desc file
    tools/pplint.py <checkpoint-dir>         # CheckpointManager root:
                                             # lints the program recorded
                                             # in the newest VALID snapshot
    tools/pplint.py <ckpt>/step_100          # one snapshot (its program
                                             # hash-verified before lint)
    tools/pplint.py dir --deploy serving     # + row-independence etc.
                                             # under the serving context
    tools/pplint.py dir --deploy decode --max-slots 8
    tools/pplint.py dir --deploy training --plan plan.json
    tools/pplint.py dir --json               # machine-readable findings
    tools/pplint.py dir --fail-on warning    # CI severity threshold
    tools/pplint.py --all-models             # sweep the bundled model
                                             # zoo under every applicable
                                             # context (the tier-1 leg)

Accepted formats (auto-detected from the first bytes):
  * native versioned JSON desc (core/program_desc.py)        -> b'{'
  * round-1 legacy pickle                                    -> b'\\x80'
  * era-wire ProgramDesc protobuf (reference_format.py)      -> anything
    else; the wire-level feed/fetch carrier checks run BEFORE the desc
    is parsed, then the parsed program goes through the full pipeline.

Feed/fetch targets come from __model_meta__.json (native dirs) or the
era feed/fetch plumbing ops (strip_feed_fetch).

Exit codes:
  0  no findings at or above the --fail-on threshold
     (default threshold: error)
  1  findings at/above the threshold (details on stdout; in --json
     mode, as one JSON document)
  2  bad invocation / unreadable or unverifiable model artifact

--strict is kept as an alias for --fail-on warning.
"""
import argparse
import json
import os
import sys

# lint must never dial a TPU tunnel / take the exclusive client lock
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _resolve_checkpoint_dir(path):
    """Map a checkpoint layout onto the program desc it records, or None
    when `path` is not a checkpoint. Accepts a checkpoint ROOT (step_<N>
    dirs / LATEST: the newest snapshot whose hash tree verifies wins,
    like CheckpointManager.restore) or one snapshot dir (snapshot.json:
    linted exactly as given — corruption is a hard error here, since the
    user pointed at THIS snapshot). Both paths verify only what the lint
    reads (structure, manifest hash, the program's own sha256) — array
    payloads are ptpu_ckpt verify's job, not GBs of reads for a lint."""
    from paddle_tpu.checkpoint import snapshot as snap
    if os.path.exists(os.path.join(path, snap.SNAPSHOT_FILE)):
        problems = snap.verify_snapshot_light(path)
        if problems:
            raise ValueError("corrupt snapshot %s: %s"
                             % (path, "; ".join(problems)))
        meta = snap.read_snapshot_meta(path)
    elif snap.list_steps(path) or os.path.exists(
            os.path.join(path, snap.LATEST_FILE)):
        # newest-first walk, but only as much hashing as the lint needs:
        # structure + manifest hash + the recorded program's own sha256
        # (verify_snapshot_light) — NOT every array file, which on a real
        # checkpoint is GBs of reads for zero lint value
        meta = None
        for _, cand in reversed(snap.list_steps(path)):
            if snap.verify_snapshot_light(cand):
                continue
            meta, path = snap.read_snapshot_meta(cand), cand
            break
        if meta is None:
            raise ValueError("checkpoint dir %s has no snapshot that "
                             "verifies" % path)
    else:
        return None
    prog = meta.get("program")
    if not prog:
        raise ValueError("snapshot %s records no program (legacy "
                         "io.save_checkpoint layout)" % path)
    return os.path.join(path, prog["file"])


def load_program(path, model_filename=None, allow_pickle=False):
    """-> (program, feed_names, fetch_names, wire_diagnostics)."""
    import paddle_tpu as fluid
    from paddle_tpu import reference_format as rf
    from paddle_tpu.analysis import check_wire_carriers

    meta_feeds = meta_fetches = None
    if os.path.isdir(path):
        ckpt_desc = _resolve_checkpoint_dir(path)
        if ckpt_desc is not None:
            # training-checkpoint program: no feed/fetch contract is
            # recorded; analysis falls back to the is_data convention
            path = ckpt_desc
        else:
            meta_path = os.path.join(path, "__model_meta__.json")
            if os.path.exists(meta_path):
                with open(meta_path) as f:
                    meta = json.load(f)
                meta_feeds, meta_fetches = (meta.get("feed"),
                                            meta.get("fetch"))
            path = os.path.join(path, model_filename or "__model__")
    with open(path, "rb") as f:
        raw = f.read()

    if raw[:1] == b"{":  # native versioned JSON desc
        program = fluid.Program.parse_from_string(raw)
        return program, meta_feeds, meta_fetches, []
    if raw[:1] == b"\x80":  # round-1 legacy pickle artifact
        # unpickling EXECUTES code from the file — never do that by
        # default in a lint tool whose whole job is inspecting artifacts
        # of unknown provenance
        if not allow_pickle:
            raise ValueError(
                "legacy pickle desc: unpickling executes code from the "
                "file; pass --allow-pickle only for artifacts you trust")
        import pickle
        program = pickle.loads(raw)
        return program, meta_feeds, meta_fetches, []
    # era-wire protobuf: carrier checks at the WIRE level first, then
    # parse (which strips the feed/fetch plumbing) and the layout adapter.
    # A malformation that also breaks parsing must still REPORT the wire
    # diagnostics that explain it, not vanish behind a load error.
    blocks = rf._parse_blocks(raw)
    wire_diags = check_wire_carriers(blocks)
    try:
        program = rf.parse_program_desc(blocks)
        feeds, fetches = rf.strip_feed_fetch(blocks)
        rf.adapt_sequence_layout(program, feeds)
    except Exception:
        if wire_diags:
            return None, None, None, wire_diags
        raise
    return program, meta_feeds or feeds, meta_fetches or fetches, wire_diags


def build_deploy_context(kind, program, feeds, fetches, plan_path=None,
                         max_slots=8, weights_dtype=None):
    """DeploymentContext for a SAVED program, mirroring what the engines
    derive at load: serving classifies each fetch by the engine's row
    policy (leading -1 = sliced rows), decode infers the slot vars from
    the executor's own state analysis, training arms a saved plan JSON
    through the device-free PlanView."""
    from paddle_tpu import analysis
    from paddle_tpu.core.utils import find_var
    if kind == "serving":
        row, whole = [], []
        for n in fetches or ():
            var = find_var(program, n)
            shape = list(getattr(var, "shape", None) or []) \
                if var is not None else []
            if (var is not None and not var.persistable and shape
                    and shape[0] == -1):
                row.append(n)
            else:
                whole.append(n)
        return analysis.DeploymentContext.for_serving(
            row_fetches=row, whole_fetches=whole,
            weights_dtype=weights_dtype)
    if kind == "decode":
        slots = analysis.infer_slot_vars(program, fetches, max_slots)
        return analysis.DeploymentContext.for_decode(
            slot_vars=slots, max_slots=max_slots,
            row_fetches=list(fetches or ()))
    if kind == "training":
        plan = None
        if plan_path:
            with open(plan_path) as f:
                plan = analysis.PlanView.from_json(json.load(f))
        return analysis.DeploymentContext.for_training(plan=plan)
    return analysis.DeploymentContext.generic()


def _diag_json(d):
    return {"severity": d.severity, "code": d.code, "message": d.message,
            "block": d.block_idx, "op": d.op_idx, "op_type": d.op_type,
            "vars": list(d.var_names), "hint": d.hint,
            "callstack": [list(fr) for fr in d.callstack]}


def _result_json(target, result):
    return {"target": target,
            "errors": len(result.errors),
            "warnings": len(result.warnings),
            "certificates": dict(result.certificates),
            "diagnostics": [_diag_json(d) for d in result.diagnostics]}


def _fails(result, fail_on):
    return bool(result.errors
                or (fail_on == "warning" and result.warnings))


def _lint_all_models(args):
    """Sweep the bundled model zoo: every model's training program under
    the generic deployment context AND under an auto-built ShardingPlan
    (1-device mesh — the plan/program coherence rules are device-count
    independent). One process, <15s: this is the tier-1 CI leg."""
    from paddle_tpu import analysis
    from paddle_tpu.models import zoo
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.plan import ShardingPlan

    mesh = make_mesh({"dp": 1})
    reports, bad = [], 0
    for name in zoo.names():
        main, _startup = zoo.build(name)
        contexts = [("generic", analysis.DeploymentContext.generic())]
        try:
            plan = ShardingPlan.build(main, mesh, shard_update=True)
            contexts.append(("training+plan",
                             analysis.DeploymentContext.for_training(
                                 plan=plan)))
        except Exception as e:  # pragma: no cover - partitioner gap
            print("pplint: %s: plan build failed (%s); generic only"
                  % (name, e), file=sys.stderr)
        for ckind, deploy in contexts:
            result = analysis.analyze(main, deploy=deploy)
            target = "%s[%s]" % (name, ckind)
            reports.append((target, result))
            if _fails(result, args.fail_on):
                bad += 1
    if args.json:
        print(json.dumps({"models": [_result_json(t, r)
                                     for t, r in reports]}, indent=2))
    else:
        for target, result in reports:
            for d in result:
                print("%s: %s" % (
                    target, d.format(with_callstack=not args.no_callstack)))
            print("pplint: %d error(s), %d warning(s) in %s"
                  % (len(result.errors), len(result.warnings), target))
    return 1 if bad else 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="pplint", description="static verifier for saved programs")
    ap.add_argument("path", nargs="?", default=None,
                    help="model directory or program desc file")
    ap.add_argument("--model-filename", default=None,
                    help="desc filename inside a model dir "
                         "(default __model__)")
    ap.add_argument("--steps", type=int, default=1,
                    help="validate for Executor.run(steps=K) semantics")
    ap.add_argument("--deploy", default=None,
                    choices=["serving", "decode", "training", "generic"],
                    help="also run the deployment-pass tier under this "
                         "context (row-independence, sharding, dtype "
                         "flow, decode invariants, donation safety)")
    ap.add_argument("--plan", default=None, metavar="PLAN_JSON",
                    help="ShardingPlan JSON (plan.to_json()) to check "
                         "the program against (--deploy training)")
    ap.add_argument("--max-slots", type=int, default=8,
                    help="decode slot count for --deploy decode")
    ap.add_argument("--weights-dtype", default=None,
                    choices=["fp32", "bf16", "int8"],
                    help="serving weights dtype the deployment expects")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as one JSON document on stdout")
    ap.add_argument("--fail-on", default="error",
                    choices=["error", "warning"],
                    help="lowest severity that makes the exit code 1 "
                         "(default: error)")
    ap.add_argument("--all-models", action="store_true",
                    help="lint every bundled model zoo program under "
                         "all applicable deployment contexts")
    ap.add_argument("--strict", action="store_true",
                    help="alias for --fail-on warning")
    ap.add_argument("--no-callstack", action="store_true",
                    help="omit op creation stacks from output")
    ap.add_argument("--allow-pickle", action="store_true",
                    help="permit loading round-1 legacy pickle descs "
                         "(unpickling executes code — trusted files only)")
    args = ap.parse_args(argv)
    if args.strict:
        args.fail_on = "warning"

    if args.all_models:
        return _lint_all_models(args)
    if args.path is None:
        ap.error("need a model path (or --all-models)")

    try:
        program, feeds, fetches, wire_diags = load_program(
            args.path, args.model_filename,
            allow_pickle=args.allow_pickle)
    except Exception as e:
        print("pplint: cannot load %s: %s" % (args.path, e),
              file=sys.stderr)
        return 2

    from paddle_tpu import analysis
    if program is None:
        # wire carrier errors AND an unparseable desc: the diagnostics
        # are the explanation — report them instead of a bare load error
        result = analysis.AnalysisResult(wire_diags)
    else:
        deploy = None
        if args.deploy:
            try:
                deploy = build_deploy_context(
                    args.deploy, program, feeds, fetches,
                    plan_path=args.plan, max_slots=args.max_slots,
                    weights_dtype=args.weights_dtype)
            except Exception as e:
                print("pplint: cannot build %s deployment context: %s"
                      % (args.deploy, e), file=sys.stderr)
                return 2
        result = analysis.analyze(program, feed_names=feeds,
                                  fetch_names=fetches, steps=args.steps,
                                  deploy=deploy)
        result.diagnostics[:0] = wire_diags  # wire findings lead, in order

    if args.json:
        print(json.dumps(_result_json(args.path, result), indent=2))
    else:
        for d in result:
            print(d.format(with_callstack=not args.no_callstack))
        print("pplint: %d error(s), %d warning(s) in %s"
              % (len(result.errors), len(result.warnings), args.path))
    return 1 if _fails(result, args.fail_on) else 0


if __name__ == "__main__":
    sys.exit(main())
