#!/usr/bin/env python3
"""ptpu_cache — operate on the persistent AOT compile-artifact cache
(paddle_tpu/core/compile_cache.py).

    tools/ptpu_cache.py inspect <cache-dir> [--json]
        List every entry: key hash, artifact size, jax version,
        platform/device kind, program hash, multistep signature, compile
        seconds recorded, age.

    tools/ptpu_cache.py verify <cache-dir>
        Re-hash every entry's payload against its meta.json. Exit 1 if
        any entry is corrupt (torn write, bit flip, hand edit) — the
        deploy-gate form: "will every warm start actually load?"

    tools/ptpu_cache.py gc <cache-dir> [--max-age-days N]
                       [--max-total-mb N] [--dry-run]
        Apply retention (age window, then newest-first size budget —
        the checkpoint retention discipline) and sweep dead writers'
        tmp droppings. --dry-run exits 1 when it WOULD delete
        (ptpu_ckpt gc's documented contract).

Exit codes: 0 ok, 1 findings (corrupt entries / would-delete in
--dry-run), 2 bad invocation.
"""
import argparse
import json
import os
import sys
import time

# a cache tool must never dial a TPU tunnel / take the client lock
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _human_size(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return "%.1f%s" % (n, unit) if unit != "B" else "%dB" % n
        n /= 1024.0


def _entry_record(path, meta):
    from paddle_tpu.core import compile_cache as cc
    key = (meta or {}).get("key", {})
    return {
        "path": path,
        "key_hash": (meta or {}).get("key_hash",
                                     os.path.basename(path)[len("aot_"):]),
        "readable": meta is not None,
        "size_bytes": cc.entry_size_bytes(path),
        "payload_bytes": (meta or {}).get("payload_bytes"),
        "jax_version": key.get("jax_version"),
        "platform": key.get("platform"),
        "device_kind": key.get("device_kind"),
        "num_devices": key.get("num_devices"),
        "program_sha256": key.get("program_sha256"),
        "fetch_names": key.get("fetch_names"),
        "multi": key.get("multi"),
        "compile_seconds": (meta or {}).get("compile_seconds"),
        "created_at": (meta or {}).get("created_at"),
    }


def cmd_inspect(args):
    from paddle_tpu.core import compile_cache as cc
    entries = cc.list_entries(args.dir)
    records = [_entry_record(p, m) for p, m in entries]
    if args.json:
        print(json.dumps({
            "cache_dir": args.dir,
            "entries": records,
            "total_bytes": sum(r["size_bytes"] for r in records),
        }, indent=1))
        return 0
    if not records:
        print("ptpu_cache: no entries under %s" % args.dir)
        return 0
    now = time.time()
    for r in records:
        age = "?" if not r["created_at"] else \
            "%.1fh" % ((now - r["created_at"]) / 3600.0)
        print("%s  %-8s jax=%-8s %s/%s x%s  compile=%.2fs  age=%s%s"
              % (r["key_hash"][:16], _human_size(r["size_bytes"]),
                 r["jax_version"], r["platform"], r["device_kind"] or "-",
                 r["num_devices"], r["compile_seconds"] or 0.0, age,
                 "" if r["readable"] else "  [META UNREADABLE]"))
        print("    program=%s  fetch=%s  multi=%s"
              % ((r["program_sha256"] or "?")[:16],
                 ",".join(r["fetch_names"] or []) or "-", r["multi"]))
    print("ptpu_cache: %d entr%s, %s total"
          % (len(records), "y" if len(records) == 1 else "ies",
             _human_size(sum(r["size_bytes"] for r in records))))
    return 0


def cmd_verify(args):
    from paddle_tpu.core import compile_cache as cc
    entries = cc.list_entries(args.dir)
    if not entries:
        print("ptpu_cache: no entries under %s" % args.dir)
        return 0
    bad = 0
    for path, meta in entries:
        problems = cc.verify_entry(path)
        name = os.path.basename(path)
        if problems:
            bad += 1
            print("%s: CORRUPT" % name)
            for p in problems:
                print("    %s" % p)
        else:
            print("%s: ok" % name)
    print("ptpu_cache: %d/%d entr%s verify"
          % (len(entries) - bad, len(entries),
             "y" if len(entries) == 1 else "ies"))
    return 1 if bad else 0


def cmd_gc(args):
    from paddle_tpu.core import compile_cache as cc
    doomed, kept = cc.gc_aot_cache(
        args.dir, max_age_days=args.max_age_days,
        max_total_mb=args.max_total_mb, dry_run=args.dry_run)
    verb = "would delete" if args.dry_run else "deleted"
    print("%s: %d entr%s (%d kept)"
          % (verb, len(doomed), "y" if len(doomed) == 1 else "ies",
             len(kept)))
    for path in doomed:
        print("    %s" % os.path.basename(path))
    if args.dry_run:
        return 1 if doomed else 0  # documented: would-delete = findings
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ptpu_cache",
        description="inspect / verify / gc the AOT compile-artifact "
                    "cache")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("inspect", help="list entries with key metadata")
    p.add_argument("dir")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_inspect)

    p = sub.add_parser("verify", help="hash-check every entry")
    p.add_argument("dir")
    p.set_defaults(fn=cmd_verify)

    p = sub.add_parser("gc", help="apply retention to the cache")
    p.add_argument("dir")
    p.add_argument("--max-age-days", type=float, default=None)
    p.add_argument("--max-total-mb", type=float, default=None)
    p.add_argument("--dry-run", action="store_true")
    p.set_defaults(fn=cmd_gc)

    args = ap.parse_args(argv)
    if not os.path.isdir(args.dir):
        print("ptpu_cache: %s is not a directory" % args.dir,
              file=sys.stderr)
        return 2
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
