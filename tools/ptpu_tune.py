#!/usr/bin/env python3
"""ptpu_tune — search and record execution configs (paddle_tpu.tuning).

    tools/ptpu_tune.py list [--store DIR] [--json]
        Every recorded config: signature, device, knobs, score,
        when/what was searched.

    tools/ptpu_tune.py show <signature> [--device KEY] [--store DIR]
                       [--json]
        One entry in full (device defaults to this host's cpu key).

    tools/ptpu_tune.py train-smoke [--store DIR] [--k 1,2,4,8]
                       [--steps 32] [--layers 12] [--hidden 32]
                       [--batch 16] [--json]
        Zero-to-tuned on the built-in dispatch-bound MLP: search
        multistep K on CPU, record the winner, print the result — the
        subprocess-tested path and the template for tuning a real model
        (see paddle_tpu.tuning.tune_training_multistep /
        tune_serving_batching for programs and serving engines).

Exit codes: 0 ok, 1 nothing found (list/show on empty store), 2 bad
invocation.
"""
import argparse
import json
import os
import sys

# a tuning CLI on the smoke model must never dial a TPU tunnel; real-
# model tuning runs go through the python API on the target device
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _store(args):
    from paddle_tpu.tuning import TuningStore
    return TuningStore(root=args.store)


def cmd_list(args):
    entries = _store(args).entries()
    if args.json:
        print(json.dumps({"entries": entries}, indent=1))
        return 0 if entries else 1
    if not entries:
        print("ptpu_tune: no recorded configs")
        return 1
    for e in entries:
        print("%s  @ %s" % (e.get("signature"), e.get("device_key")))
        print("    knobs=%s  score=%s %s"
              % (e.get("knobs"), e.get("score"), e.get("score_unit")))
    return 0


def cmd_show(args):
    st = _store(args)
    dev = args.device
    if dev is None:
        import jax
        from paddle_tpu.tuning import device_key
        dev = device_key(jax.devices("cpu")[0])
    entry = st.get(args.signature, dev)
    if entry is None:
        print("ptpu_tune: no config for %r @ %r"
              % (args.signature, dev), file=sys.stderr)
        return 1
    print(json.dumps(entry, indent=1, sort_keys=True))
    return 0


def cmd_train_smoke(args):
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import tuning

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main_prog,
                                                        startup):
        x = fluid.layers.data(name="x", shape=[args.hidden],
                              dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = x
        for _ in range(args.layers):
            h = fluid.layers.fc(input=h, size=args.hidden, act="relu")
        p = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.mean(
            x=fluid.layers.square_error_cost(input=p, label=y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)

    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(args.batch, args.hidden).astype("float32"),
            "y": rng.rand(args.batch, 1).astype("float32")}
    ks = [int(k) for k in args.k.split(",") if k.strip()]
    # scan lowering keeps the K>1 compiles cheap enough for a smoke CLI
    os.environ.setdefault("FLAGS_multistep_unroll", "0")
    store = (tuning.TuningStore(root=args.store) if args.store
             else tuning.TuningStore())
    result = tuning.tune_training_multistep(
        main_prog, startup, feed, [loss], k_candidates=ks,
        steps=args.steps, warmup=1, repeats=2, store=store,
        verbose=not args.json)
    record = {
        "signature": tuning.program_signature(main_prog),
        "best": result.best,
        "best_score": result.best_score,
        "score_unit": result.score_unit,
        "results": [{"knobs": k, "score": s, "error": e}
                    for k, s, e in result.results],
        "store_path": result.store_path,
    }
    print(json.dumps(record) if args.json
          else "recorded %s (%.1f %s) -> %s"
          % (result.best, result.best_score, result.score_unit,
             result.store_path))
    return 0


def cmd_kernels(args):
    """Kernel block-knob sweep (tuning.tune_kernels): per (op,
    shape-bucket, device_kind) tile search + the flash-vs-dense
    crossover, recorded so every later process dispatches at the tuned
    tiles (ops/kernel_config.py reads the store at trace time)."""
    if args.place == "tpu":
        # the module-level CPU pin must not leak into a hardware tune;
        # jax has not initialized yet (it imports lazily below)
        if os.environ.get("JAX_PLATFORMS") == "cpu":
            del os.environ["JAX_PLATFORMS"]
    from paddle_tpu import tuning
    ops = tuple(o.strip() for o in args.ops.split(",") if o.strip())
    shapes = None
    if args.smoke:
        # tiny shapes: the subprocess-tested zero-to-tuned path (CPU
        # interpret mode; real sweeps drop --smoke and run on TPU)
        shapes = {"attn": [dict(b=1, h=1, d=8, t=16)],
                  "xent": [dict(n=16, v=64)],
                  "ln": [dict(n=16, d=32)],
                  "lstm": [dict(b=4, t=8, d=8)],
                  "seq": [dict(b=8, t=16)]}
    store = (tuning.TuningStore(root=args.store) if args.store
             else tuning.TuningStore())
    result = tuning.tune_kernels(
        ops=ops, shapes=shapes, repeats=args.repeats, store=store,
        include_crossover=not args.no_crossover,
        verbose=not args.json)
    record = {
        "entries": {sig: {"best": r.best, "best_score": r.best_score,
                          "score_unit": r.score_unit,
                          "store_path": r.store_path}
                    for sig, r in result["entries"].items()},
        "crossover": result["crossover"],
        "store": store.root,
    }
    if args.json:
        print(json.dumps(record))
    else:
        for sig, r in sorted(record["entries"].items()):
            print("%s -> %s (%.1f %s)" % (sig, r["best"], r["best_score"],
                                          r["score_unit"]))
        if record["crossover"] is not None:
            print("flash crossover -> min_seq=%d" % record["crossover"])
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ptpu_tune",
        description="search and record execution configs")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("list", help="every recorded config")
    p.add_argument("--store", default=None)
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("show", help="one config in full")
    p.add_argument("signature")
    p.add_argument("--device", default=None,
                   help="device key 'platform/kind' (default: host cpu)")
    p.add_argument("--store", default=None)
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_show)

    p = sub.add_parser("train-smoke",
                       help="tune multistep K on the built-in MLP")
    p.add_argument("--store", default=None)
    p.add_argument("--k", default="1,2,4,8")
    p.add_argument("--steps", type=int, default=32)
    p.add_argument("--layers", type=int, default=12)
    p.add_argument("--hidden", type=int, default=32)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_train_smoke)

    p = sub.add_parser("kernels",
                       help="sweep pallas tile/block knobs per "
                            "(op, shape-bucket, device_kind)")
    p.add_argument("--store", default=None)
    p.add_argument("--ops", default="attn,xent,ln,lstm,seq")
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--place", default="cpu", choices=["cpu", "tpu"],
                   help="tpu = tune on the real chip (the only numbers "
                        "worth recording for deploy; cpu interpret mode "
                        "exists for the smoke path)")
    p.add_argument("--smoke", action="store_true",
                   help="tiny shapes (seconds on CPU) — the tested "
                        "zero-to-tuned path")
    p.add_argument("--no-crossover", action="store_true",
                   help="skip the flash-vs-dense crossover measurement")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_kernels)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
