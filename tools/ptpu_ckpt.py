#!/usr/bin/env python3
"""ptpu_ckpt — operate on CheckpointManager checkpoint directories.

    tools/ptpu_ckpt.py inspect <ckpt-dir> [--step N] [--json]
        Manifest, step, seed cursor, reader states, per-file hashes of
        one snapshot (default: the newest valid one).

    tools/ptpu_ckpt.py verify <ckpt-dir>
        Hash-check EVERY published snapshot. Exit 1 if any snapshot's
        hash tree fails — the deploy-gate form: "is every checkpoint in
        this directory loadable?"

    tools/ptpu_ckpt.py gc <ckpt-dir> --max-to-keep N [--keep-every M]
                       [--dry-run]
        Apply a retention policy offline (the same engine the manager
        runs after each save) and sweep dead writers' tmp droppings.

Exit codes: 0 ok, 1 findings (corruption / would-delete in --dry-run
when nothing matches is still 0), 2 bad invocation.
"""
import argparse
import json
import os
import sys

# a checkpoint tool must never dial a TPU tunnel / take the client lock
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _human_size(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return "%.1f%s" % (n, unit) if unit != "B" else "%dB" % n
        n /= 1024.0


def cmd_inspect(args):
    from paddle_tpu.checkpoint import snapshot as snap
    found = snap.find_valid_snapshot(args.dir, step=args.step)
    if found is None:
        print("ptpu_ckpt: no %s snapshot under %s"
              % ("valid step_%s" % args.step if args.step is not None
                 else "valid", args.dir), file=sys.stderr)
        return 1
    step, path = found
    meta = snap.read_snapshot_meta(path)
    manifest = snap.load_manifest(path)
    record = {
        "step": step,
        "path": path,
        "legacy": bool(meta.get("legacy")),
        "seed_cursor": meta.get("seed_cursor"),
        "program_version": meta.get("program_version"),
        "program_sha256": (meta.get("program") or {}).get("sha256"),
        "reader_states": meta.get("reader_states") or {},
        "num_vars": len(manifest),
        "total_bytes": sum(
            os.path.getsize(os.path.join(path, e["file"]))
            for e in manifest.values()),
        "vars": {
            name: {"shape": e.get("shape"), "dtype": e.get("dtype"),
                   "is_param": e.get("is_param"),
                   "owner": e.get("owner"), "sha256": e.get("sha256")}
            for name, e in sorted(manifest.items())},
        "all_steps": [s for s, _ in snap.list_steps(args.dir)],
        "latest_pointer": snap.read_latest_pointer(args.dir),
    }
    if args.json:
        print(json.dumps(record, indent=1))
        return 0
    print("snapshot step_%d  (%s)" % (step, path))
    print("  legacy=%s seed_cursor=%s program_version=%s"
          % (record["legacy"], record["seed_cursor"],
             record["program_version"]))
    print("  %d vars, %s" % (record["num_vars"],
                             _human_size(record["total_bytes"])))
    for name, e in record["vars"].items():
        owner = ""
        if e.get("owner"):
            owner = "  <- %s" % e["owner"]
        elif e.get("owner") == "":
            owner = "  <- (optimizer global)"
        print("    %-40s %-12s %s%s"
              % (name, e.get("dtype"), e.get("shape"), owner))
    for rname, st in record["reader_states"].items():
        print("  reader %s: %s" % (rname, st))
    print("  steps on disk: %s  LATEST-> %s"
          % (record["all_steps"], record["latest_pointer"]))
    return 0


def cmd_verify(args):
    from paddle_tpu.checkpoint import snapshot as snap
    steps = snap.list_steps(args.dir)
    if not steps:
        print("ptpu_ckpt: no snapshots under %s" % args.dir,
              file=sys.stderr)
        return 1
    bad = 0
    for step, path in steps:
        problems = snap.verify_snapshot(path)
        if problems:
            bad += 1
            print("step_%d: CORRUPT" % step)
            for p in problems:
                print("    %s" % p)
        else:
            legacy = snap.read_snapshot_meta(path).get("legacy")
            print("step_%d: ok%s" % (step,
                                     " (legacy, unhashed)" if legacy
                                     else ""))
    print("ptpu_ckpt: %d/%d snapshot(s) verify" % (len(steps) - bad,
                                                   len(steps)))
    return 1 if bad else 0


def cmd_gc(args):
    from paddle_tpu.checkpoint import RetentionPolicy, apply_retention
    from paddle_tpu.checkpoint import snapshot as snap
    policy = RetentionPolicy(max_to_keep=args.max_to_keep,
                             keep_every_n_steps=args.keep_every)
    steps = [s for s, _ in snap.list_steps(args.dir)]
    doomed = policy.to_delete(steps)
    if args.dry_run:
        print("would delete: %s (keeping %s)"
              % (doomed, [s for s in steps if s not in doomed]))
        return 1 if doomed else 0  # documented: would-delete = findings
    deleted = apply_retention(args.dir, policy)
    print("deleted: %s (keeping %s)"
          % (deleted, [s for s, _ in snap.list_steps(args.dir)]))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ptpu_ckpt",
        description="inspect / verify / gc checkpoint directories")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("inspect", help="describe one snapshot")
    p.add_argument("dir")
    p.add_argument("--step", type=int, default=None,
                   help="pin a step (default: newest valid)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    p.set_defaults(fn=cmd_inspect)

    p = sub.add_parser("verify", help="hash-check every snapshot")
    p.add_argument("dir")
    p.set_defaults(fn=cmd_verify)

    p = sub.add_parser("gc", help="apply a retention policy offline")
    p.add_argument("dir")
    p.add_argument("--max-to-keep", type=int, required=True)
    p.add_argument("--keep-every", type=int, default=None,
                   help="also keep every Nth step")
    p.add_argument("--dry-run", action="store_true")
    p.set_defaults(fn=cmd_gc)

    args = ap.parse_args(argv)
    if not os.path.isdir(args.dir):
        print("ptpu_ckpt: %s is not a directory" % args.dir,
              file=sys.stderr)
        return 2
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
