#!/usr/bin/env python3
"""ptpu_serve — serve a saved model over HTTP with dynamic micro-batching.

    tools/ptpu_serve.py <model-dir> [--port 8080] [--host 127.0.0.1]
        [--format auto|native|reference] [--params-filename NAME]
        [--name NAME] [--place cpu|tpu] [--replicas N] [--tp M]
        [--warmup-buckets 1,4,8x32,8x64] [--max-batch 32]
        [--max-delay-ms 5] [--deadline-ms N] [--queue-capacity 256]

`--replicas N` serves N engine replicas behind one endpoint (a
`serving.ReplicaPool`): least-loaded routing, per-replica health-gated
circuit breakers, failover with bounded retry, adaptive admission, and
zero-downtime weight reload. /metrics labels every serving family
{model, replica}; /healthz carries the pool state.

`--warmup-buckets` configures the (batch, seq) lattice: bare integers are
batch buckets, `BxS` pairs add S to the seq-bucket set (sequence models
warm the full batch-buckets x seq-buckets product). Endpoints:
/v1/models, /v1/models/<name>:predict, /healthz, /metrics.

Deploy smoke gate:

    tools/ptpu_serve.py <model-dir> --selfcheck 32

loads the model, fires N random requests through the REAL batcher from
concurrent threads, compares every response bit-for-bit against a direct
single-request Executor.run at the same bucket, prints a verdict, and
exits nonzero on any mismatch — wire it before flipping traffic. With
`--replicas N --kill-replica IDX` the gate hard-kills replica IDX while
the first wave of requests is in flight and submits a second wave after:
any client-visible error fails the deploy — the failover invariant
(traffic redistributes with zero dropped requests) as a gate.

Generative decode deploys (`--decode`): serve a state-carrying decode-
step export through iteration-level continuous batching
(serving.DecodeEngine, ARCHITECTURE.md §27) — `--max-slots` concurrent
streams per replica, `--max-new-tokens` default token budget,
`--stream-deadline-ms` per-stream deadline; POST :decode streams NDJSON.
`--decode --selfcheck N` fires N concurrent streams with mixed token
budgets through the REAL continuous batcher and compares every stream
token-for-token against a solo decode of the same feed (a clone sharing
the weights) — bit-exactness under slot reuse as the deploy gate.
"""
import argparse
import json
import os
import signal
import sys
import threading

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def parse_buckets(spec):
    """'1,4,8x32,8x64' -> (batch_buckets=[1,4,8], seq_buckets=[32,64])."""
    if not spec:
        return None, None
    batch, seq = set(), set()
    for part in spec.split(","):
        part = part.strip().lower()
        if not part:
            continue
        if "x" in part:
            b, s = part.split("x", 1)
            batch.add(int(b))
            seq.add(int(s))
        else:
            batch.add(int(part))
    return sorted(batch) or None, sorted(seq) or None


def selfcheck(engine, n_requests, rows_max=4, seed=0, kill_replica=None,
              reference=None, divergence_bound=0.0, stats=None):
    """Fire n random requests through the batcher concurrently; verify
    each against run_direct at the bucket the batch actually used.
    Returns the number of mismatches (submit failures count).

    kill_replica (pools only): hard-kill that replica index MID-GATE —
    the first half of the requests is in flight when the replica dies,
    the second half is submitted after. Any client-visible error or bit
    mismatch fails the gate: this is the failover invariant (traffic
    redistributes with zero dropped requests) as a deploy check.

    reference (quantized deploys): an fp32 engine over the SAME model —
    each response is additionally compared against the fp32 run_direct
    at the same bucket, and max |q - f| / (max|f| + 1e-6) over
    `divergence_bound` counts as a mismatch (the bounded-divergence
    gate of weights_dtype serving). stats, when passed, gets
    {"max_divergence": float} filled in."""
    import time

    import numpy as np
    rng = np.random.RandomState(seed)
    rows_max = max(1, min(rows_max, engine.max_batch_size))
    feed_specs = engine.describe()["feeds"]
    requests = []
    for _ in range(n_requests):
        rows = int(rng.randint(1, rows_max + 1))
        feed = {}
        for spec in feed_specs:
            name, dtype = spec["name"], spec["dtype"] or "float32"
            if spec["sequence"]:
                feat = [d if d >= 0 else 1 for d in spec["shape"][2:]]
                max_s = engine.seq_buckets[-1] if engine.seq_buckets else 8
                lens = rng.randint(1, max(2, max_s // 2), size=rows)
                if "int" in dtype:
                    feed[name] = [rng.randint(0, 4, [int(l)] + feat)
                                  .astype(dtype) for l in lens]
                else:
                    feed[name] = [rng.randn(*([int(l)] + feat))
                                  .astype(dtype) for l in lens]
            else:
                feat = [d if d >= 0 else 1 for d in spec["shape"][1:]]
                if "int" in dtype:
                    feed[name] = rng.randint(0, 4, [rows] + feat) \
                        .astype(dtype)
                else:
                    feed[name] = rng.randn(*([rows] + feat)).astype(dtype)
        requests.append(feed)

    from paddle_tpu.serving import QueueFullError
    futures = [None] * n_requests

    # the gate tests BIT-EXACTNESS, not deadline shedding: a server-level
    # --deadline-ms default would false-fail the whole check the moment
    # the first uncached bucket compiles (hundreds of ms); disable it for
    # the selfcheck traffic and restore after
    saved_deadline = engine.default_deadline_ms
    engine.default_deadline_ms = None

    def fire(i):
        deadline = time.monotonic() + 30
        while True:
            try:
                futures[i] = engine.submit(requests[i])
                return
            except QueueFullError:       # smoke gate: back off, retry
                if time.monotonic() > deadline:
                    futures[i] = QueueFullError("retries exhausted")
                    return
                time.sleep(0.005)
            except Exception as e:  # noqa: BLE001 — a gate must report,
                futures[i] = e      # not die with a thread traceback
                return

    threads = [threading.Thread(target=fire, args=(i,))
               for i in range(n_requests)]
    if kill_replica is None:
        for t in threads:
            t.start()
    else:
        # two waves around the kill: wave 1 is in flight (some of it
        # queued ON the victim) when the replica dies, wave 2 arrives
        # after — both must come back complete and bit-exact
        half = max(1, n_requests // 2)
        for t in threads[:half]:
            t.start()
        time.sleep(0.05)          # let wave 1 spread across the queues
        engine.kill_replica(kill_replica)
        for t in threads[half:]:
            t.start()
    for t in threads:
        t.join()
    engine.default_deadline_ms = saved_deadline

    mismatches = 0
    max_div = 0.0
    for i, fut in enumerate(futures):
        if not hasattr(fut, "result"):   # submit failed: counts as fail
            mismatches += 1
            print("selfcheck FAILED SUBMIT: request %d: %r" % (i, fut),
                  file=sys.stderr)
            continue
        try:
            got = fut.result(120).numpy()
        except Exception as e:  # noqa: BLE001
            mismatches += 1
            print("selfcheck FAILED REQUEST: %d: %r" % (i, e),
                  file=sys.stderr)
            continue
        want, _ = engine.run_direct(requests[i],
                                    batch_bucket=fut.bucket[0],
                                    seq_bucket=fut.bucket[1])
        for name in engine.fetch_names:
            if not np.array_equal(got[name], want[name]):
                mismatches += 1
                print("selfcheck MISMATCH: request %d fetch %r "
                      "(bucket %r)" % (i, name, fut.bucket),
                      file=sys.stderr)
                break
        if reference is not None:
            ref, _ = reference.run_direct(requests[i],
                                          batch_bucket=fut.bucket[0],
                                          seq_bucket=fut.bucket[1])
            for name in engine.fetch_names:
                f = np.asarray(ref[name], dtype=np.float64)
                q = np.asarray(got[name], dtype=np.float64)
                div = float(np.abs(q - f).max()
                            / (np.abs(f).max() + 1e-6)) if f.size else 0.0
                max_div = max(max_div, div)
                if div > divergence_bound:
                    mismatches += 1
                    print("selfcheck DIVERGENCE: request %d fetch %r: "
                          "%.3e > bound %.3e" % (i, name, div,
                                                 divergence_bound),
                          file=sys.stderr)
                    break
    if stats is not None:
        stats["max_divergence"] = max_div
    return mismatches


def decode_selfcheck(engine, n_streams, seed=0, max_new_tokens=16,
                     rows_from=None):
    """The --decode deploy gate: N concurrent streams with mixed token
    budgets through the real continuous batcher (admits/retires under
    slot reuse), each compared token-for-token against a solo decode of
    the same feed through a clone sharing the weights. Returns the
    number of mismatched streams (submit/stream failures count)."""
    import numpy as np
    rng = np.random.RandomState(seed)
    solo_src = rows_from or (engine.replicas[0]
                             if hasattr(engine, "replicas") else engine)
    specs = solo_src.describe()["slot_vars"]
    feeds, budgets = [], []
    for i in range(n_streams):
        f = {}
        for spec in specs:
            shape, dtype = spec["row_shape"], spec["dtype"] or "float32"
            if "bool" in dtype:
                f[spec["name"]] = rng.randint(0, 2, shape).astype(dtype)
            elif "int" in dtype:
                f[spec["name"]] = rng.randint(0, 4, shape).astype(dtype)
            else:
                f[spec["name"]] = rng.randn(*shape).astype(dtype)
        feeds.append(f)
        budgets.append(int(rng.randint(max(2, max_new_tokens // 2),
                                       max_new_tokens + 1)))

    streams = [None] * n_streams

    def fire(i):
        try:
            streams[i] = engine.submit(feeds[i],
                                       max_new_tokens=budgets[i])
        except Exception as e:  # noqa: BLE001 — a gate must report,
            streams[i] = e      # not die with a thread traceback

    threads = [threading.Thread(target=fire, args=(i,))
               for i in range(n_streams)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    mismatches, got = 0, {}
    for i, s in enumerate(streams):
        if not hasattr(s, "result"):
            mismatches += 1
            print("decode selfcheck FAILED SUBMIT: stream %d: %r"
                  % (i, s), file=sys.stderr)
            continue
        try:
            got[i] = np.asarray(s.result(300)).reshape(-1)
        except Exception as e:  # noqa: BLE001
            mismatches += 1
            print("decode selfcheck FAILED STREAM: %d: %r" % (i, e),
                  file=sys.stderr)

    solo = solo_src.solo_clone(name="selfcheck-solo")
    try:
        for i, toks in sorted(got.items()):
            want = np.asarray(solo.decode(
                feeds[i], max_new_tokens=budgets[i])).reshape(-1)
            if toks.shape != want.shape or not np.array_equal(toks, want):
                mismatches += 1
                print("decode selfcheck MISMATCH: stream %d: batched %s "
                      "vs solo %s" % (i, toks.tolist(), want.tolist()),
                      file=sys.stderr)
    finally:
        solo.close()
    return mismatches


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ptpu_serve",
        description="batched online inference server for saved models")
    ap.add_argument("model_dir")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--format", default="auto",
                    choices=["auto", "native", "reference"])
    ap.add_argument("--model-filename", default=None)
    ap.add_argument("--params-filename", default=None)
    ap.add_argument("--name", default=None,
                    help="model name in URLs (default: dir basename)")
    ap.add_argument("--place", default="cpu", choices=["cpu", "tpu"])
    ap.add_argument("--warmup-buckets", default=None,
                    help="e.g. 1,4,8x32,8x64 (BxS adds a seq bucket)")
    ap.add_argument("--no-warmup", action="store_true",
                    help="skip startup tracing (first requests compile)")
    ap.add_argument("--max-batch", type=int, default=None,
                    help="max coalesced rows per dispatch (default: the "
                         "largest batch bucket, or 32 with no explicit "
                         "buckets)")
    ap.add_argument("--max-delay-ms", type=float, default=5.0)
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="default per-request deadline (requests may "
                         "override per call)")
    ap.add_argument("--queue-capacity", type=int, default=256)
    ap.add_argument("--pipeline-depth", type=int, default=None,
                    metavar="D",
                    help="continuous-batching in-flight window: up to D "
                    "dispatches outstanding per engine while the next "
                    "batch forms (default 2; 0 = the serial batcher)")
    ap.add_argument("--replicas", type=int, default=1, metavar="N",
                    help="serve N engine replicas behind one endpoint "
                         "(least-loaded routing, health-gated circuit "
                         "breakers, failover, zero-downtime reload) — "
                         "round-robin over the visible devices")
    ap.add_argument("--autoscale", default=None, metavar="MIN,MAX",
                    help="self-scaling pool: grow/shrink replicas "
                         "between MIN and MAX off the admission/queue/"
                         "idle signals (serving.PoolAutoscaler); "
                         "--replicas is the starting size (default "
                         "MIN). Scale-up rides the AOT warm start; "
                         "scale-down drains, never drops")
    ap.add_argument("--extra-model", action="append", default=[],
                    metavar="NAME=DIR[@PRIORITY]",
                    help="serve additional models from one process (a "
                         "serving.ModelFleet): repeatable; each extra "
                         "model gets its own replica pool with the "
                         "same engine config. Priorities drive fleet "
                         "brownout — the LOWEST priority tier sheds "
                         "first under overload (default 0)")
    ap.add_argument("--priority", type=int, default=0,
                    help="the main model's fleet priority (only "
                         "meaningful with --extra-model)")
    ap.add_argument("--tp", type=int, default=None, metavar="M",
                    help="tensor parallelism: each replica spans M "
                         "devices (weights sharded 1/M per chip by the "
                         "ShardingPlan's row/col rule — serve models "
                         "bigger than one chip); replica i owns the "
                         "contiguous device span [i*M, (i+1)*M). "
                         "/metrics + /healthz expose each replica's "
                         "span")
    ap.add_argument("--attempt-timeout-s", type=float, default=30.0,
                    help="pool failover: per-replica attempt timeout "
                         "(how long a wedged replica can hold a request "
                         "before it retries elsewhere)")
    ap.add_argument("--hedge-delay-ms", type=float, default=None,
                    help="pool tail hedging: duplicate a quiet request "
                         "onto a second replica after this delay")
    ap.add_argument("--selfcheck", type=int, default=0, metavar="N",
                    help="fire N local requests through the batcher, "
                         "verify bit-exactness vs direct runs, exit "
                         "(nonzero on any mismatch) — deploy smoke gate")
    ap.add_argument("--kill-replica", type=int, default=None,
                    metavar="IDX",
                    help="with --selfcheck on a --replicas pool: hard-"
                         "kill replica IDX mid-gate; ANY client-visible "
                         "error fails the gate (the failover invariant "
                         "as a deploy check)")
    ap.add_argument("--weights-dtype", default=None,
                    choices=["fp32", "bf16", "int8"],
                    help="weight precision at load: bf16 halves weight "
                         "HBM + runs MXU ops bf16; int8 stores matmul/"
                         "conv weights per-channel quantized behind an "
                         "in-graph dequantize (fp32 master files "
                         "untouched). --selfcheck additionally gates "
                         "max divergence vs a local fp32 engine")
    ap.add_argument("--decode", action="store_true",
                    help="serve a state-carrying decode-step export with "
                         "iteration-level continuous batching (one batch "
                         "row slot per stream, admits/retires between "
                         "decode iterations; POST :decode streams "
                         "NDJSON). --replicas N builds a DecodePool")
    ap.add_argument("--max-slots", type=int, default=8, metavar="S",
                    help="--decode: concurrent streams per replica (the "
                         "fixed compiled batch dimension)")
    ap.add_argument("--max-new-tokens", type=int, default=128,
                    metavar="T",
                    help="--decode: default per-stream token budget "
                         "(requests may override per call)")
    ap.add_argument("--stream-deadline-ms", type=float, default=None,
                    help="--decode: default per-stream deadline, "
                         "admission to last token")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    if args.decode and (args.autoscale or args.extra_model
                        or args.weights_dtype or args.tp
                        or args.kill_replica is not None):
        ap.error("--decode does not compose with --autoscale/"
                 "--extra-model/--weights-dtype/--tp/--kill-replica")
    if args.kill_replica is not None and not args.selfcheck:
        ap.error("--kill-replica requires --selfcheck")
    if args.kill_replica is not None and args.replicas < 2:
        ap.error("--kill-replica needs --replicas >= 2 (killing the only "
                 "replica cannot redistribute anything)")
    autoscale = None
    if args.autoscale:
        try:
            lo_s, hi_s = args.autoscale.split(",", 1)
            autoscale = (int(lo_s), int(hi_s))
        except ValueError:
            ap.error("--autoscale wants MIN,MAX (e.g. 1,4)")
        if autoscale[0] < 1 or autoscale[1] < autoscale[0]:
            ap.error("--autoscale wants 1 <= MIN <= MAX")
        if args.replicas > autoscale[1]:
            ap.error("--replicas %d starts above --autoscale MAX %d; "
                     "the controller could never shrink past its own "
                     "ceiling" % (args.replicas, autoscale[1]))
    extra_models = []
    for spec in args.extra_model:
        if "=" not in spec:
            ap.error("--extra-model wants NAME=DIR[@PRIORITY], got %r"
                     % spec)
        mname, _, rest = spec.partition("=")
        mdir, _, prio = rest.partition("@")
        extra_models.append((mname.strip(), mdir.strip(),
                             int(prio) if prio else 0))
    if extra_models and args.selfcheck:
        ap.error("--selfcheck gates one model; run it per model dir")

    if args.place == "cpu":
        # only pin the platform for an explicitly-CPU server, and only
        # BEFORE jax initializes — with --place tpu the env must stay
        # untouched or the image's axon platform silently falls back to
        # CPU and "serves" on the wrong device
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import paddle_tpu as fluid
    from paddle_tpu import serving

    # serving warmup is the cold start that hurts most: pre-tracing the
    # whole bucket lattice recompiles every shape on every restart.
    # Default BOTH compile caches on (per-uid dirs) so a restarted
    # server loads its lattice from disk; FLAGS_compile_cache_dir='' /
    # FLAGS_aot_cache_dir='' stay the explicit off switches.
    from paddle_tpu.core.compile_cache import (
        default_aot_cache_dir, default_cache_dir,
        maybe_enable_aot_cache, maybe_enable_persistent_cache)
    maybe_enable_persistent_cache(default_cache_dir())
    maybe_enable_aot_cache(default_aot_cache_dir())

    batch_buckets, seq_buckets = parse_buckets(args.warmup_buckets)
    engine_kw = dict(
        model_format=args.format, model_filename=args.model_filename,
        params_filename=args.params_filename, name=args.name,
        batch_buckets=batch_buckets, seq_buckets=seq_buckets,
        max_batch_size=args.max_batch,
        max_queue_delay_ms=args.max_delay_ms,
        queue_capacity=args.queue_capacity, warmup=not args.no_warmup,
        pipeline_depth=args.pipeline_depth,
        weights_dtype=args.weights_dtype)
    fleet = None
    try:
        if args.decode:
            place = (fluid.TPUPlace() if args.place == "tpu"
                     else fluid.CPUPlace())
            base = args.name or os.path.basename(
                os.path.normpath(args.model_dir))
            dec_kw = dict(
                model_format=args.format,
                model_filename=args.model_filename,
                params_filename=args.params_filename, place=place,
                max_slots=args.max_slots,
                queue_capacity=args.queue_capacity,
                default_max_new_tokens=args.max_new_tokens,
                default_deadline_ms=args.stream_deadline_ms,
                warmup=not args.no_warmup)
            if args.replicas > 1:
                engine = serving.DecodePool(
                    [serving.DecodeEngine(args.model_dir,
                                          name="%s-%d" % (base, i),
                                          **dec_kw)
                     for i in range(args.replicas)], name=base)
            else:
                engine = serving.DecodeEngine(args.model_dir, name=base,
                                              **dec_kw)
        elif args.replicas > 1 or autoscale or extra_models:
            # pool placement: None = TPUPlace(i) round-robin over the
            # visible accelerators; an explicit --place cpu pins all
            # replicas to the host backend
            engine_kw.pop("name")
            pool_kw = dict(
                replicas=args.replicas, tp=args.tp,
                place=fluid.CPUPlace() if args.place == "cpu" else None,
                default_deadline_ms=args.deadline_ms,
                attempt_timeout_s=args.attempt_timeout_s,
                hedge_delay_ms=args.hedge_delay_ms, **engine_kw)
            if autoscale:
                pool_kw.update(autoscale=True,
                               min_replicas=autoscale[0],
                               max_replicas=autoscale[1],
                               replicas=max(args.replicas, autoscale[0]))
            engine = serving.ReplicaPool(args.model_dir, name=args.name,
                                         **pool_kw)
            if extra_models:
                fleet = serving.ModelFleet()
                fleet.add_model(engine.name, pool=engine,
                                priority=args.priority)
                for mname, mdir, prio in extra_models:
                    fleet.add_model(mname, priority=prio,
                                    model_dir=mdir, **pool_kw)
        else:
            place = (fluid.TPUPlace() if args.place == "tpu"
                     else fluid.CPUPlace())
            engine = serving.InferenceEngine(
                args.model_dir, place=place, tp=args.tp,
                default_deadline_ms=args.deadline_ms, **engine_kw)
    except fluid.ProgramVerificationError as e:
        print("ptpu_serve: model REJECTED by the static verifier:\n%s"
              % e, file=sys.stderr)
        return 2

    if args.selfcheck and args.decode:
        bad = decode_selfcheck(engine, args.selfcheck,
                               max_new_tokens=min(args.max_new_tokens,
                                                  16))
        reps = (engine.replicas if hasattr(engine, "replicas")
                else [engine])
        snaps = [r.decode_stats() for r in reps]
        iters = sum(s["iterations"] for s in snaps)
        record = {
            "selfcheck": "pass" if bad == 0 else "fail",
            "mode": "decode", "streams": args.selfcheck,
            "mismatches": bad,
            "max_slots": args.max_slots,
            "iterations": iters,
            "tokens_total": sum(s["tokens_total"] for s in snaps),
            # >1 proves streams actually SHARED iterations (continuous
            # batching engaged), not that they queued up serially
            "mean_slot_occupancy": round(
                sum(s["iterations"] * s["mean_slot_occupancy"]
                    for s in snaps) / max(iters, 1), 3)}
        if hasattr(engine, "pool_state"):
            record["replicas"] = args.replicas
            record["pool"] = engine.pool_state()
        print(json.dumps(record))
        engine.close()
        return 1 if bad else 0

    if args.selfcheck:
        reference, bound = None, 0.0
        if args.weights_dtype in ("bf16", "int8"):
            # the bounded-divergence gate: a local fp32 twin of the
            # model (no batcher needed — selfcheck drives run_direct)
            from paddle_tpu.serving.quantize import divergence_bound
            ref_kw = dict(engine_kw, weights_dtype=None, warmup=False,
                          name="fp32-reference")
            reference = serving.InferenceEngine(
                args.model_dir,
                place=(fluid.TPUPlace() if args.place == "tpu"
                       else fluid.CPUPlace()), **ref_kw)
            bound = divergence_bound(args.weights_dtype)
        qstats = {}
        bad = selfcheck(engine, args.selfcheck,
                        kill_replica=args.kill_replica,
                        reference=reference, divergence_bound=bound,
                        stats=qstats)
        if reference is not None:
            reference.close()
        if hasattr(engine, "replica_metrics"):   # pool: aggregate
            snaps = [m.snapshot()
                     for m in engine.replica_metrics().values()]
            batches = sum(s["batches_total"] for s in snaps)
            occupancy = round(
                sum(s["batches_total"] * s["mean_batch_occupancy"]
                    for s in snaps) / max(batches, 1), 3)
        else:
            snap = engine.metrics.snapshot()
            batches = snap["batches_total"]
            occupancy = snap["mean_batch_occupancy"]
        record = {
            "selfcheck": "pass" if bad == 0 else "fail",
            "requests": args.selfcheck, "mismatches": bad,
            "mean_batch_occupancy": occupancy, "batches": batches}
        if args.weights_dtype:
            record["weights_dtype"] = args.weights_dtype
        if reference is not None:
            record["max_divergence"] = round(
                qstats.get("max_divergence", 0.0), 6)
            record["divergence_bound"] = bound
        if hasattr(engine, "pool_state"):
            record["replicas"] = args.replicas
            # pool_state carries per-replica engine config
            # (weights_dtype, pipeline_depth, tp, devices): a deploy
            # that accidentally mixed configs is VISIBLE in the gate
            # output, not silent
            record["pool"] = engine.pool_state()
            if args.kill_replica is not None:
                record["killed_replica"] = args.kill_replica
        else:
            record["engine"] = {
                "weights_dtype": engine.weights_dtype,
                "pipeline_depth": engine.pipeline_depth,
                "tp": engine.tp}
        print(json.dumps(record))
        engine.close()
        return 1 if bad else 0

    server = serving.ModelServer(fleet if fleet is not None else engine,
                                 host=args.host, port=args.port,
                                 verbose=args.verbose)
    if args.decode:
        print("ptpu_serve: %r (decode, %d slots x %d replicas) on "
              "http://%s — POST /v1/models/%s:decode streams NDJSON"
              % (engine.name, args.max_slots, args.replicas,
                 server.address, engine.name))
    else:
        print("ptpu_serve: %r (%s) on http://%s — buckets batch=%s "
              "seq=%s%s"
              % (engine.name, args.format, server.address,
                 engine.batch_buckets, engine.seq_buckets or "-",
                 " + %d extra models" % len(extra_models)
                 if extra_models else ""))

    def handle_sig(signum, frame):
        # only unblock serve_forever from a side thread here (calling the
        # blocking httpd.shutdown() on the main thread would deadlock);
        # the DRAIN runs synchronously on the main thread below, so the
        # process cannot exit before in-flight batches complete
        threading.Thread(target=server.httpd.shutdown,
                         daemon=True).start()

    signal.signal(signal.SIGTERM, handle_sig)
    signal.signal(signal.SIGINT, handle_sig)  # Ctrl-C takes the same
    server.serve_forever()                    # drain path as SIGTERM
    server.shutdown()   # idempotent: stop loop, drain engines, join
    return 0


if __name__ == "__main__":
    sys.exit(main())
