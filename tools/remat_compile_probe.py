"""Probe: segment-remat compile-time scaling (round-4 verdict weak #3 —
remat@512 died in a >20-min XLA compile on the real chip).

Builds the ResNet-50 train program with/without segment remat, lowers it,
counts optimization barriers in the emitted HLO, and times trace and
compile separately. Runs anywhere (CPU by default — XLA:CPU's pass
pipeline is not XLA:TPU's, but the barrier count and trace cost are
backend-independent, and a superlinear compile blowup reproducible here
is fixable here).

Usage:
  JAX_PLATFORMS=cpu python tools/remat_compile_probe.py [batch ...]
Env:
  PROBE_REMAT=0/1, FLAGS_remat_segment_len=N (forwarded to the lowering),
  PROBE_HW (default 224), PROBE_CLASSES (default 1000).
One JSON line per config.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from paddle_tpu import tpu_guard  # noqa: E402,F401 - lock guard installs


def probe(batch, remat, hw, classes):
    import jax
    # the axon sitecustomize forces jax_platforms="axon,cpu" in CONFIG
    # regardless of the env var; honor an explicit request so CPU probe
    # runs never dial the tunnel (same rule as bench.py/_await)
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        jax.config.update("jax_platforms", want)
    # compile-time probe: do NOT enable the persistent cache here — a
    # cache hit would report near-zero compile_s and invalidate the
    # measurement this tool exists for
    import paddle_tpu as fluid
    from paddle_tpu.core import lowering

    main, startup = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main, startup):
        from paddle_tpu.models.image_classification import build_train
        image, label, avg_cost, acc = build_train(
            model="resnet50", class_dim=classes, image_shape=(3, hw, hw),
            learning_rate=0.1, momentum=0.9, use_bf16=True)
    if remat:
        fluid.memory_optimization_transpiler.enable_rematerialization(main)

    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        state_rw, state_ro, state_out = lowering.analyze_state(
            main, ["image", "label"])
        fn = lowering.build_program_fn(
            main, ["image", "label"], [avg_cost.name],
            state_rw, state_ro, state_out)
        rw = [np.asarray(scope.get(n)) for n in state_rw]
        ro = [np.asarray(scope.get(n)) for n in state_ro]

    xs = np.zeros((batch, 3, hw, hw), np.float32)
    ys = np.zeros((batch, 1), np.int64)

    t0 = time.perf_counter()
    lowered = jax.jit(fn).lower([xs, ys], rw, ro, np.uint32(0))
    t_trace = time.perf_counter() - t0
    hlo = lowered.as_text()
    n_barrier = hlo.count("optimization_barrier")
    n_lines = hlo.count("\n")
    t0 = time.perf_counter()
    compiled = lowered.compile()
    t_compile = time.perf_counter() - t0
    del compiled
    print(json.dumps({
        "probe": "remat_compile", "batch": batch, "remat": bool(remat),
        # RESOLVED value (clamped/validated), not the raw env string —
        # banked numbers must be labeled with the config that actually ran
        "segment_len": lowering.remat_segment_len_flag(),
        "hw": hw, "classes": classes,
        "trace_s": round(t_trace, 2), "compile_s": round(t_compile, 2),
        "hlo_barriers": n_barrier, "hlo_lines": n_lines,
        "device": str(jax.devices()[0])}), flush=True)


def main():
    batches = [int(a) for a in sys.argv[1:]] or [64]
    remat = os.environ.get("PROBE_REMAT", "1") == "1"
    hw = int(os.environ.get("PROBE_HW", "224"))
    classes = int(os.environ.get("PROBE_CLASSES", "1000"))
    for b in batches:
        probe(b, remat, hw, classes)


if __name__ == "__main__":
    main()
