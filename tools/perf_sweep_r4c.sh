#!/bin/bash
# DEPRECATED SHIM (PR 19): the round-4c sweep script was superseded by
# r5 then r6 and finally by the declarative tier queue in
# paddle_tpu/benchd/tiers.py (drained by tools/ptpu_bench.py run /
# daemon).  Kept as a shim so any stale crontab or notes pointing here
# still bank lines through the store instead of silently diverging.
set -u
cd "$(dirname "$0")/.."
exec python tools/ptpu_bench.py run --git-bank "$@"
