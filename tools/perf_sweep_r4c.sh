#!/bin/bash
# Round-4 third-window sweep: everything still unmeasured after the 03:15Z
# window. SUPERSEDES perf_sweep.sh / perf_sweep_r4b.sh (historical records
# of earlier windows — do not re-run them; this copy carries the harness
# fixes: rc-gated banking, probe-before-recovery-log). Cheapest-first; ONE client at a time via tools/tpu_lock.sh;
# stderr kept per run. New since the last window: pallas flash BACKWARD
# kernels (dK/dV + dQ, causal skipping) and segment-level remat replaced
# the per-op jax.checkpoint that OOM'd at 29G.
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/perf_sweep_r4c.log
: > $LOG
WEDGED=0
N=0
LOCK="tools/tpu_lock.sh"
tunnel_ok() {
  bash "$LOCK" timeout 120 python -c "import jax; print(jax.devices())" \
    >/dev/null 2>&1
}
probe() {
  [ "$WEDGED" = 1 ] && return 1
  tunnel_ok && return 0
  local rc=$?
  if [ $rc -eq 75 ]; then
    echo "- $(date -u +%FT%TZ) r4c sweep stopped: tpu_lock busy (rc=75)" >> BENCH_LOG.md
  else
    echo "- $(date -u +%FT%TZ) tunnel probe FAILED mid-r4c-sweep" >> BENCH_LOG.md
  fi
  WEDGED=1
  return 1
}
bank() {
  git commit -q -m "perf sweep: bank measured bench lines" \
    -- BENCH_LOG.md 2>/dev/null || true
}
run() {  # run <timeout_s> ENV=V...
  [ "$WEDGED" = 1 ] && { echo "skip (wedged): $*" | tee -a $LOG; return; }
  local to=$1; shift
  N=$((N+1))
  echo "=== [$N] $*" | tee -a $LOG
  local line rc
  bash "$LOCK" env "$@" BENCH_DEVICE_TIMEOUT=300 timeout -k 10 "$to" \
    python bench.py >/tmp/bench_run.out 2>/tmp/bench_err_c$N.log
  rc=$?
  if [ $rc -eq 75 ]; then
    echo "- $(date -u +%FT%TZ) r4c sweep stopped mid-run: tpu_lock busy" >> BENCH_LOG.md
    WEDGED=1
    return
  fi
  line=$(tail -1 /tmp/bench_run.out)
  echo "$line" | tee -a $LOG
  # rc gates banking: a timeout-killed run's last stdout line must never
  # be banked as a measurement (r4c review finding)
  if [ $rc -ne 0 ]; then
    line='{"error": "rc='$rc'"}'"$line"
  fi
  case "$line" in
    *'"error"'*|"")
      echo "- $(date -u +%FT%TZ) FAILED(rc=$rc, err=/tmp/bench_err_c$N.log): $*" >> BENCH_LOG.md
      tail -3 /tmp/bench_err_c$N.log >> $LOG
      case "$line" in
        *"device init"*) WEDGED=1 ;;
        *) tunnel_ok || WEDGED=1 ;;
      esac ;;
    *) printf -- '- %s `%s`\n  `%s`\n' "$(date -u +%FT%TZ)" "$*" "$line" \
         >> BENCH_LOG.md
       bank ;;
  esac
}
probe || exit 1
echo "- $(date -u +%FT%TZ) TUNNEL RECOVERED; r4c sweep starts" >> BENCH_LOG.md
# tier 1: headline re-confirmation (the round-4 Env/lowering changes sit
# on every trace path) then cheap re-measures through the NEW flash
# backward kernels
run 900 BENCH_BATCH=256 BENCH_DTYPE=bf16
probe && run 900 BENCH_MODEL=transformer BENCH_BATCH=32 BENCH_SEQ=256
probe && run 900 BENCH_MODEL=transformer BENCH_BATCH=4 BENCH_SEQ=2048 BENCH_STEPS=5 BENCH_WARMUP=2
probe && run 900 BENCH_MODEL=transformer BENCH_BATCH=32 BENCH_SEQ=256 BENCH_FUSED_QKV=1
probe && run 900 BENCH_MODEL=transformer BENCH_DECODE=1 BENCH_BATCH=16 BENCH_SEQ=128
# tier 2: new bench models
probe && run 900 BENCH_MODEL=stacked_lstm BENCH_BATCH=128 BENCH_SEQ=64
probe && run 900 BENCH_MODEL=vgg16 BENCH_BATCH=128
# tier 3: flash block-size tuning sweep (one process, many small compiles)
if probe; then
  echo "=== flash tune" | tee -a $LOG
  bash "$LOCK" env MB_TUNE=1 timeout 1500 python tools/pallas_microbench.py \
    2>/tmp/bench_err_ctune.log | tee -a $LOG | \
    while read -r line; do
      printf -- '- %s flash_tune `%s`\n' "$(date -u +%FT%TZ)" "$line" >> BENCH_LOG.md
    done
  [ "${PIPESTATUS[0]:-0}" = 0 ] || \
    echo "- $(date -u +%FT%TZ) FAILED: flash tune (err=/tmp/bench_err_ctune.log)" >> BENCH_LOG.md
  bank
fi
# tier 4: big compiles LAST — segment-remat graphs compile long (the
# 03:48Z remat@512 attempt died at its own 20-min timeout, no OOM)
probe && run 2400 BENCH_BATCH=512 BENCH_DTYPE=bf16 BENCH_STEPS=5 BENCH_WARMUP=2 BENCH_REMAT=1
probe && run 1200 BENCH_BATCH=1024 BENCH_DTYPE=bf16 BENCH_STEPS=5 BENCH_WARMUP=2
probe && run 2400 BENCH_BATCH=1024 BENCH_DTYPE=bf16 BENCH_STEPS=5 BENCH_WARMUP=2 BENCH_REMAT=1
bank
echo "=== r4c sweep done (wedged=$WEDGED) ===" | tee -a $LOG
# propagate wedge status so the probe loop can leave the sweep queued
# (a wedged run refires on the next healthy window)
exit $WEDGED
