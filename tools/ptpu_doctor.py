#!/usr/bin/env python3
"""ptpu_doctor — inspect and replay resilience diagnostic bundles.

A bundle is what the Supervisor/watchdog captures when a training fault
escalates (resilience/watchdog.py write_bundle): the program, the
failing step's feeds and persistable state, the recent-metrics ring,
the event log, and every thread's stack at capture time.

    tools/ptpu_doctor.py inspect <bundle-dir> [--json]
        Human (or JSON) summary: reason, fault class, step, error,
        feed shapes, metrics ring, recovery events, thread stacks.

    tools/ptpu_doctor.py trace <bundle-dir | trace-dump.json>
            [--out chrome.json] [--last N]
        Render the flight-recorder timeline a bundle embeds
        (paddle_tpu.observability.trace, ARCHITECTURE.md §24): the
        recorded span ring in time order plus every span still OPEN at
        capture — for a hang bundle, the open spans ARE the answer to
        "what was the pipeline doing when it wedged". --out writes
        Chrome trace-event JSON for chrome://tracing / Perfetto.

    tools/ptpu_doctor.py replay <bundle-dir> [--fetch NAME ...]
        Re-run the RECORDED failing step offline: load the bundled
        program, put the bundled persistable state into a fresh scope,
        dispatch the bundled feeds once (guards and all, on CPU).
        Exit 1 when the fault REPRODUCES (same class of failure —
        that is the actionable result: the bundle alone demonstrates
        the bug); exit 0 when the step replays clean (the fault was
        environmental: preemption, a dying reader host, a flaky link).

Exit codes: 0 replayed clean / inspected, 1 fault reproduced,
2 bundle unreplayable (no program/feeds captured) or bad invocation.
"""
import argparse
import json
import os
import sys

# a diagnosis tool must never dial a TPU tunnel / take the client lock
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def cmd_inspect(args):
    from paddle_tpu.resilience.watchdog import read_bundle
    meta, program, feeds, state = read_bundle(args.bundle)
    if args.json:
        out = dict(meta)
        out["has_feeds"] = feeds is not None
        out["has_state"] = state is not None
        out["num_state_vars"] = 0 if state is None else len(state)
        print(json.dumps(out, indent=1, sort_keys=True))
        return 0
    print("bundle:      %s" % args.bundle)
    print("reason:      %s" % meta.get("reason"))
    print("fault class: %s" % meta.get("fault_class"))
    print("step:        %s" % meta.get("step"))
    print("error:       %s" % meta.get("error"))
    print("program:     %s" % ("recorded (v%s)" % meta.get(
        "program_version") if program is not None else "absent"))
    print("feeds:       %s" % (", ".join(
        "%s%s" % (n, s[0]) for n, s in sorted(
            meta.get("feed_shapes", {}).items())) or "absent"))
    print("state vars:  %d captured, %d unavailable"
          % (0 if state is None else len(state),
             len(meta.get("state_unavailable", []))))
    for ev in meta.get("events", [])[-8:]:
        print("event:       step %s %s:%s %s"
              % (ev.get("step"), ev.get("class"), ev.get("action"),
                 ev.get("error") or ""))
    for m in list(meta.get("metrics", []))[-5:]:
        print("metric:      %s" % m)
    for name in sorted(meta.get("thread_stacks", {})):
        print("thread:      %s" % name)
    return 0


def cmd_trace(args):
    from paddle_tpu.observability import trace as otrace
    target = args.bundle
    data = None
    if os.path.isdir(target):
        # a watchdog/supervisor bundle OR a cluster merged bundle —
        # both carry their recorder dump under "trace" in bundle.json
        meta_path = os.path.join(target, "bundle.json")
        if not os.path.exists(meta_path):
            print("ptpu_doctor: %r has no bundle.json" % target,
                  file=sys.stderr)
            return 2
        with open(meta_path) as f:
            meta = json.load(f)
        data = meta.get("trace")
        if data is None:
            print("TRACE UNSUPPORTED: bundle predates the flight "
                  "recorder (no 'trace' key in bundle.json)")
            return 2
    else:
        with open(target) as f:
            raw = json.load(f)
        # accept a bundle.json, a bare dump(), or nothing usable
        data = raw.get("trace") if "trace" in raw else raw
        if not isinstance(data, dict) or "events" not in data:
            print("ptpu_doctor: %r carries no recorder dump "
                  "(want a bundle dir, bundle.json, or a "
                  "trace.dump() JSON)" % target, file=sys.stderr)
            return 2
    if args.out:
        otrace.export_chrome_trace(args.out, data=data)
        print("chrome trace written: %s (load in chrome://tracing or "
              "https://ui.perfetto.dev)" % args.out)
    print(otrace.render_timeline(data, last=args.last))
    return 0


def cmd_replay(args):
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.core.compile_cache import (default_cache_dir,
                                               maybe_enable_persistent_cache)
    from paddle_tpu.core.executor import NumericalGuardError
    from paddle_tpu.resilience.watchdog import read_bundle
    # a replay of a remat-heavy training step pays the same compile the
    # wedged trainer did; the persistent cache makes repeat replays (and
    # a replay on the machine that trained) load it from disk instead
    maybe_enable_persistent_cache(default_cache_dir())
    meta, program, feeds, state = read_bundle(args.bundle)
    if program is None or feeds is None:
        print("REPLAY UNSUPPORTED: bundle carries %s" % (
            "no program" if program is None else
            "feed shapes only (reader-fed step; arrays not captured)"))
        return 2
    if meta.get("state_unavailable"):
        # a post-timeout capture with donated-and-gone buffers: a
        # replay against partial state would raise replay-ENVIRONMENT
        # errors and masquerade as a reproduction
        print("REPLAY UNSUPPORTED: %d state var(s) were unavailable at "
              "capture (%s...) — the bundle cannot re-create the "
              "failing step's inputs"
              % (len(meta["state_unavailable"]),
                 ", ".join(meta["state_unavailable"][:3])))
        return 2
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        for name, arr in (state or {}).items():
            scope.set(name, arr)
        fetch = list(args.fetch or [])
        try:
            # the replay rides the same watchdog it diagnoses: a
            # hang-class bundle that REPRODUCES must exit 1, not wedge
            # the doctor
            out = exe.run(program, feed=dict(feeds), fetch_list=fetch,
                          timeout=float(args.timeout))
        except fluid.DispatchTimeoutError as e:
            if meta.get("fault_class") == "hang":
                print("REPRODUCED: replaying step %s hung past %.0fs "
                      "(%s)" % (meta.get("step"), float(args.timeout), e))
                return 1
            print("REPLAY ERROR: replay hung past %.0fs but the bundle "
                  "records a %r fault" % (float(args.timeout),
                                          meta.get("fault_class")))
            return 2
        except Exception as e:  # noqa: BLE001 — classified below
            # the verdict requires the raise to MATCH the recorded
            # fault class: a numeric bundle reproduces only via the
            # numerical guard — any other raise here is a replay
            # problem, not a reproduction
            if meta.get("fault_class") == "numeric" and not isinstance(
                    e, NumericalGuardError):
                print("REPLAY ERROR: expected a numerical-guard trip "
                      "but replay raised %s: %s" % (type(e).__name__, e))
                return 2
            print("REPRODUCED: replaying step %s raised %s: %s"
                  % (meta.get("step"), type(e).__name__, e))
            return 1
    for name, v in zip(fetch, out):
        print("fetch %s = %s" % (name, np.asarray(v).reshape(-1)[:8]))
    print("CLEAN: step %s replayed without a fault (environmental "
          "failure — preemption, reader host, link?)" % meta.get("step"))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="ptpu_doctor")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("inspect", help="summarize a bundle")
    p.add_argument("bundle")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_inspect)
    p = sub.add_parser("trace", help="render a bundle's flight-recorder "
                                     "timeline")
    p.add_argument("bundle", help="bundle dir, bundle.json, or a "
                                  "trace dump JSON")
    p.add_argument("--out", default=None,
                   help="also write Chrome trace-event JSON here")
    p.add_argument("--last", default=60, type=int,
                   help="how many newest events to render (default 60)")
    p.set_defaults(fn=cmd_trace)
    p = sub.add_parser("replay", help="re-run the recorded failing step")
    p.add_argument("bundle")
    p.add_argument("--fetch", action="append", default=[],
                   help="var name(s) to fetch on a clean replay")
    p.add_argument("--timeout", default=300.0, type=float,
                   help="replay hang deadline in seconds (default 300)")
    p.set_defaults(fn=cmd_replay)
    args = ap.parse_args(argv)
    try:
        return args.fn(args)
    except FileNotFoundError as e:
        print("ptpu_doctor: %s" % e, file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
