#!/bin/bash
# Exclusive-tunnel guard: run ANY TPU-touching command as
#   bash tools/tpu_lock.sh <cmd...>
# Takes a blocking flock on /tmp/tpu_client.lock so two TPU clients can
# never overlap (the axon tunnel wedges its lease on concurrent clients —
# it cost rounds 2-3 their perf story and re-wedged round 4 at 01:52Z).
#
# A lock timeout is NOT a tunnel wedge: flock exits rc=75 (EX_TEMPFAIL,
# via -E) so callers (perf_sweep.sh) can tell "another client is still
# running" apart from "the tunnel is gone". The wrapped command's own rc
# passes through untouched.
LOCKFILE=/tmp/tpu_client.lock
# Tell the in-process guard (paddle_tpu/tpu_guard.py) the flock is already
# held by this wrapper (the locked fd is inherited through flock's exec),
# so the wrapped python process must not try to re-acquire it.
export PTPU_LOCK_HELD=1
if ! flock -n "$LOCKFILE" true 2>/dev/null; then
  echo "tpu_lock: lock busy (another TPU client is running); waiting up to 20 min..." >&2
fi
exec flock -w 1200 -E 75 "$LOCKFILE" "$@"
