#!/bin/bash
# Exclusive-tunnel guard: run ANY TPU-touching command as
#   bash tools/tpu_lock.sh <cmd...>
# Takes a blocking flock on /tmp/tpu_client.lock so two TPU clients can
# never overlap (the axon tunnel wedges its lease on concurrent clients —
# it cost rounds 2-3 their perf story and re-wedged round 4 at 01:52Z).
#
# A lock timeout is NOT a tunnel wedge: it exits rc=75 (EX_TEMPFAIL) with
# a loud stderr line so callers (perf_sweep.sh probe()) can tell "another
# client is still running" apart from "the tunnel is gone".
LOCKFILE=/tmp/tpu_client.lock
if ! flock -n "$LOCKFILE" true 2>/dev/null; then
  echo "tpu_lock: lock busy (another TPU client is running); waiting up to 20 min..." >&2
fi
flock -w 1200 "$LOCKFILE" "$@"
rc=$?
# flock's own acquisition failure returns 1 with nothing executed; re-check
# the lock to map it to a distinct, loud code (a wrapped command's real
# rc=1 passes through because the lock is free again by then)
if [ $rc -eq 1 ] && ! flock -n "$LOCKFILE" true 2>/dev/null; then
  echo "tpu_lock: TIMED OUT waiting for $LOCKFILE (rc=75, NOT a tunnel wedge)" >&2
  exit 75
fi
exit $rc
