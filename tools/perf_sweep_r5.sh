#!/bin/bash
# Round-5 sweep. SUPERSEDES perf_sweep_r4c.sh (kept as the historical
# record of the r4 queue). Cheapest-first; ONE client at a time via
# tools/tpu_lock.sh; rc-gated banking (a timeout-killed run's stdout is
# never banked); stderr kept per run. Exits nonzero when wedged so the
# probe loop leaves the sweep queued for the next healthy window.
#
# New since r4c:
# - flash per-shape dispatch landed (FLAGS_flash_min_seq, default 1024):
#   tier-1 transformer lines measure the AUTO dispatch (the headline
#   config); kernel-forced comparisons set FLAGS_flash_min_seq=0.
# - remat segment-length knob (FLAGS_remat_segment_len) — remat configs
#   probe seg lengths informed by the CPU compile probe.
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/perf_sweep_r5.log
: > $LOG
WEDGED=0
N=0
LOCK="tools/tpu_lock.sh"
tunnel_ok() {
  bash "$LOCK" timeout 120 python -c \
    'import jax,sys; sys.exit(0 if any(d.platform!="cpu" for d in jax.devices()) else 1)' \
    >/dev/null 2>&1
}
probe() {
  [ "$WEDGED" = 1 ] && return 1
  tunnel_ok && return 0
  local rc=$?
  if [ $rc -eq 75 ]; then
    echo "- $(date -u +%FT%TZ) r5 sweep stopped: tpu_lock busy (rc=75)" >> BENCH_LOG.md
  else
    echo "- $(date -u +%FT%TZ) tunnel probe FAILED mid-r5-sweep" >> BENCH_LOG.md
  fi
  WEDGED=1
  return 1
}
bank() {
  git commit -q -m "perf sweep: bank measured bench lines" \
    -- BENCH_LOG.md 2>/dev/null || true
}
run() {  # run <timeout_s> ENV=V...
  [ "$WEDGED" = 1 ] && { echo "skip (wedged): $*" | tee -a $LOG; return; }
  local to=$1; shift
  N=$((N+1))
  echo "=== [$N] $*" | tee -a $LOG
  local line rc
  bash "$LOCK" env "$@" BENCH_DEVICE_TIMEOUT=300 timeout -k 10 "$to" \
    python bench.py >/tmp/bench_run.out 2>/tmp/bench_err_r5_$N.log
  rc=$?
  if [ $rc -eq 75 ]; then
    echo "- $(date -u +%FT%TZ) r5 sweep stopped mid-run: tpu_lock busy" >> BENCH_LOG.md
    WEDGED=1
    return
  fi
  line=$(tail -1 /tmp/bench_run.out)
  if [ $rc -ne 0 ]; then
    line='{"error": "rc='$rc'"}'"$line"
  fi
  case "$line" in
    *'"error"'*|"")
      echo "- $(date -u +%FT%TZ) FAILED(rc=$rc, err=/tmp/bench_err_r5_$N.log): $*" >> BENCH_LOG.md
      tail -3 /tmp/bench_err_r5_$N.log >> $LOG
      case "$line" in
        *"device init"*) WEDGED=1 ;;
        *) tunnel_ok || WEDGED=1 ;;
      esac ;;
    *) printf -- '- %s `%s`\n  `%s`\n' "$(date -u +%FT%TZ)" "$*" "$line" \
         >> BENCH_LOG.md
       bank ;;
  esac
}
mb() {  # mb <timeout_s> <label> ENV=V... -- run pallas_microbench with env
  [ "$WEDGED" = 1 ] && { echo "skip (wedged): mb $*" | tee -a $LOG; return; }
  local to=$1 label=$2; shift 2
  echo "=== mb:$label $*" | tee -a $LOG
  bash "$LOCK" env "$@" timeout -k 10 "$to" python tools/pallas_microbench.py \
    >/tmp/mb_run.out 2>/tmp/mb_err_$label.log
  local rc=$?
  if [ $rc -eq 75 ]; then
    echo "- $(date -u +%FT%TZ) r5 sweep stopped mid-mb: tpu_lock busy" >> BENCH_LOG.md
    WEDGED=1
    return
  fi
  if [ $rc -eq 0 ]; then
    while read -r line; do
      printf -- '- %s microbench(%s) `%s`\n' "$(date -u +%FT%TZ)" "$label" "$line" >> BENCH_LOG.md
    done < /tmp/mb_run.out
    bank
  else
    echo "- $(date -u +%FT%TZ) FAILED(rc=$rc): microbench $label (err=/tmp/mb_err_$label.log)" >> BENCH_LOG.md
    tunnel_ok || WEDGED=1
  fi
}
probe || exit 1
echo "- $(date -u +%FT%TZ) TUNNEL RECOVERED; r5 sweep starts" >> BENCH_LOG.md
# --- tier 1: headline re-confirmation (cheapest, banked first) -------------
run 900 BENCH_BATCH=256 BENCH_DTYPE=bf16
probe && run 900 BENCH_MODEL=transformer BENCH_BATCH=32 BENCH_SEQ=256
# --- tier 2: the round's MFU target — transformer at T>=1024 through the
# NEW pallas bwd kernels (auto dispatch runs flash at these lengths) ------
probe && run 900 BENCH_MODEL=transformer BENCH_BATCH=8 BENCH_SEQ=1024 BENCH_STEPS=5 BENCH_WARMUP=2
probe && run 900 BENCH_MODEL=transformer BENCH_BATCH=4 BENCH_SEQ=2048 BENCH_STEPS=5 BENCH_WARMUP=2
probe && run 900 BENCH_MODEL=transformer BENCH_BATCH=4 BENCH_SEQ=2048 BENCH_STEPS=5 BENCH_WARMUP=2 BENCH_FUSED_QKV=1
# MFU scales with model width — the big config (d_model 1024, 16 heads)
# is the fairer MXU-utilization number at long T
probe && run 1200 BENCH_MODEL=transformer BENCH_BATCH=4 BENCH_SEQ=2048 BENCH_DMODEL=1024 BENCH_HEADS=16 BENCH_STEPS=5 BENCH_WARMUP=2
# kernel-level: flash fwd+bwd vs XLA dense at the long lengths (the r4
# lax bwd measured 0.75x dense; the pallas bwd must beat 1x to stay)
probe && mb 1200 bwd MB_SHAPES="8x1024x8x64,8x2048x8x64,4x4096x8x64"
# --- tier 3: decode + remaining model families -----------------------------
probe && run 900 BENCH_MODEL=transformer BENCH_DECODE=1 BENCH_BATCH=16 BENCH_SEQ=128
probe && run 900 BENCH_MODEL=stacked_lstm BENCH_BATCH=128 BENCH_SEQ=64
probe && run 900 BENCH_MODEL=vgg16 BENCH_BATCH=128
probe && run 900 BENCH_MODEL=resnet101 BENCH_BATCH=128 BENCH_DTYPE=bf16
# host-feed pair: float32 (link-bandwidth-bound on the tunnel: 40.4 img/s
# = ~24MB/s in r4) vs uint8-normalize-on-device (4x less traffic). If
# host_u8 lands ~4x host, the feeder machinery is proven and the ceiling
# is the link, closing r4 weak #5's open question.
probe && run 900 BENCH_BATCH=256 BENCH_DTYPE=bf16 BENCH_FEED=host BENCH_STEPS=5 BENCH_WARMUP=2
probe && run 900 BENCH_BATCH=256 BENCH_DTYPE=bf16 BENCH_FEED=host_u8 BENCH_STEPS=5 BENCH_WARMUP=2
# --- tier 4: flash block-size tune (one process, many small compiles) ------
if probe; then
  echo "=== flash tune" | tee -a $LOG
  bash "$LOCK" env MB_TUNE=1 FLAGS_flash_min_seq=0 timeout 1500 \
    python tools/pallas_microbench.py 2>/tmp/bench_err_r5tune.log | \
    tee -a $LOG | while read -r line; do
      printf -- '- %s flash_tune `%s`\n' "$(date -u +%FT%TZ)" "$line" >> BENCH_LOG.md
    done
  [ "${PIPESTATUS[0]:-0}" = 0 ] || \
    echo "- $(date -u +%FT%TZ) FAILED: flash tune (err=/tmp/bench_err_r5tune.log)" >> BENCH_LOG.md
  bank
fi
# --- tier 5: big compiles LAST — remat with the segment-length knob.
# Segment lengths from the CPU compile probe (tools/remat_compile_probe.py);
# 40-min budget for the first compile of each.
# CPU compile probe (tools/remat_compile_probe.py, banked in BENCH_LOG):
# XLA:CPU compiles every remat config in 16-21s at batch 64..1024
# (barriers 22/13/4 for seg_len 8/sqrt/44) — the >20-min blowup is
# TPU-pass-specific. Longest segments (fewest barriers) first, then a
# scheduler-off variant (the latency-hiding scheduler is the prime
# suspect for barrier-sensitive compile cost).
probe && run 2400 BENCH_BATCH=512 BENCH_DTYPE=bf16 BENCH_STEPS=5 BENCH_WARMUP=2 BENCH_REMAT=1 FLAGS_remat_segment_len=44
probe && run 2400 BENCH_BATCH=512 BENCH_DTYPE=bf16 BENCH_STEPS=5 BENCH_WARMUP=2 BENCH_REMAT=1
probe && run 2400 BENCH_BATCH=512 BENCH_DTYPE=bf16 BENCH_STEPS=5 BENCH_WARMUP=2 BENCH_REMAT=1 FLAGS_remat_segment_len=44 XLA_FLAGS=--xla_tpu_enable_latency_hiding_scheduler=false
probe && run 1200 BENCH_BATCH=1024 BENCH_DTYPE=bf16 BENCH_STEPS=5 BENCH_WARMUP=2
probe && run 2400 BENCH_BATCH=1024 BENCH_DTYPE=bf16 BENCH_STEPS=5 BENCH_WARMUP=2 BENCH_REMAT=1 FLAGS_remat_segment_len=44
bank
echo "=== r5 sweep done (wedged=$WEDGED) ===" | tee -a $LOG
exit $WEDGED
