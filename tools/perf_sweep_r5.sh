#!/bin/bash
# DEPRECATED SHIM (PR 19): the round-5 sweep (remat/flash tiers; never
# got a healthy window — see BENCH_LOG.md 2026-08-02) was folded into
# the declarative queue in paddle_tpu/benchd/tiers.py.  Historical
# results context lives in BENCH_LOG.md; the protocol (probe → lock →
# cheapest-first drain → rc-gated bank) is now paddle_tpu/benchd.
set -u
cd "$(dirname "$0")/.."
exec python tools/ptpu_bench.py run --git-bank "$@"
