"""Kernel-level microbench: pallas flash attention and fused softmax-xent
vs their dense XLA counterparts, fwd+bwd, on whatever backend jax exposes
(meant for the real chip; run via tools/perf_sweep.sh). One JSON line per
comparison: {"kernel": ..., "dense_ms": ..., "fused_ms": ..., "speedup":
..., "shape": ...}.

Exclusive-tunnel rule applies: never run concurrently with another TPU
process (see BENCH_LOG.md / memory notes).
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _await():
    import jax
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        jax.config.update("jax_platforms", want)
    return jax


def _time(fn, *args, iters=20, warmup=3):
    from paddle_tpu.core.utils import device_fetch_barrier
    for _ in range(warmup):
        out = fn(*args)
    device_fetch_barrier(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    device_fetch_barrier(out)
    return (time.perf_counter() - t0) / iters * 1e3


def bench_attention(b=8, t=2048, h=8, d=64, causal=True, dtype="bfloat16"):
    jax = _await()
    import jax.numpy as jnp
    from paddle_tpu.ops import pallas_kernels as pk
    from paddle_tpu.parallel.ring_attention import attention_reference

    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(b, t, h, d).astype("f") * 0.3,
                           dtype=dtype) for _ in range(3))

    def dense_loss(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal)
                       .astype(jnp.float32))

    def flash_loss(q, k, v):
        return jnp.sum(pk.flash_attention(q, k, v, causal=causal)
                       .astype(jnp.float32))

    dense = jax.jit(jax.grad(dense_loss, argnums=(0, 1, 2)))
    flash = jax.jit(jax.grad(flash_loss, argnums=(0, 1, 2)))
    dms = _time(dense, q, k, v)
    fms = _time(flash, q, k, v)
    # flush per line: a timeout-kill (tunnel wedge) must not discard
    # measurements already completed (BENCH_LOG persistence contract)
    print(json.dumps({
        "kernel": "flash_attention_fwd_bwd", "dense_ms": round(dms, 3),
        "fused_ms": round(fms, 3), "speedup": round(dms / fms, 3),
        "shape": [b, t, h, d], "causal": causal, "dtype": dtype,
        "device": str(jax.devices()[0])}), flush=True)


def bench_softmax_xent(n=8192, v=32000):
    jax = _await()
    import jax.numpy as jnp
    from paddle_tpu.ops import pallas_kernels as pk

    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(n, v).astype("f"))
    labels = jnp.asarray(rng.randint(0, v, n).astype("i4"))

    def dense(logits, labels):
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.sum(jnp.take_along_axis(logp, labels[:, None], 1))

    def fused(logits, labels):
        return jnp.sum(pk.softmax_xent(logits, labels))

    d = jax.jit(jax.grad(dense))
    f = jax.jit(jax.grad(fused))
    dms = _time(d, logits, labels)
    fms = _time(f, logits, labels)
    print(json.dumps({
        "kernel": "softmax_xent_fwd_bwd", "dense_ms": round(dms, 3),
        "fused_ms": round(fms, 3), "speedup": round(dms / fms, 3),
        "shape": [n, v], "device": str(jax.devices()[0])}), flush=True)


if __name__ == "__main__":
    # MB_* knobs shrink the config for smoke runs (CPU interpret mode is
    # orders of magnitude slower than the real kernel)
    bench_attention(b=int(os.environ.get("MB_B", "8")),
                    t=int(os.environ.get("MB_SEQ", "2048")),
                    h=int(os.environ.get("MB_H", "8")))
    bench_softmax_xent(n=int(os.environ.get("MB_N", "8192")),
                       v=int(os.environ.get("MB_V", "32000")))
