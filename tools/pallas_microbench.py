"""Kernel-level microbench: pallas flash attention and fused softmax-xent
vs their dense XLA counterparts, fwd+bwd, on whatever backend jax exposes
(meant for the real chip; run via tools/perf_sweep.sh). One JSON line per
comparison: {"kernel": ..., "dense_ms": ..., "fused_ms": ..., "speedup":
..., "shape": ...}.

Exclusive-tunnel rule applies: never run concurrently with another TPU
process (see BENCH_LOG.md / memory notes).
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


from paddle_tpu import tpu_guard  # noqa: E402 - mandatory exclusive
                                  # TPU-client lock (installs on import)


def _await():
    import jax
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        jax.config.update("jax_platforms", want)
    from paddle_tpu.core.compile_cache import (default_cache_dir,
                                               maybe_enable_persistent_cache)
    maybe_enable_persistent_cache(default_cache_dir())
    tpu_guard.require_accelerator("pallas_microbench")
    return jax


def _time(fn, *args, iters=20, warmup=3):
    from paddle_tpu.core.utils import device_fetch_barrier
    for _ in range(warmup):
        out = fn(*args)
    device_fetch_barrier(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    device_fetch_barrier(out)
    return (time.perf_counter() - t0) / iters * 1e3


def _attention_setup(b, t, h, d, causal, dtype):
    """Shared q/k/v construction + dense baseline so bench_attention and
    tune_attention_blocks stay comparable by construction."""
    import jax.numpy as jnp
    from paddle_tpu.parallel.ring_attention import attention_reference

    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(b, t, h, d).astype("f") * 0.3,
                           dtype=dtype) for _ in range(3))

    def dense_fwd(q, k, v):
        return attention_reference(q, k, v, causal=causal)

    def dense_loss(q, k, v):
        return jnp.sum(dense_fwd(q, k, v).astype(jnp.float32))

    return q, k, v, dense_fwd, dense_loss


def bench_attention(b=8, t=2048, h=8, d=64, causal=True, dtype="bfloat16"):
    jax = _await()
    import jax.numpy as jnp
    from paddle_tpu.ops import pallas_kernels as pk

    q, k, v, _, dense_loss = _attention_setup(b, t, h, d, causal, dtype)

    def flash_loss(q, k, v):
        return jnp.sum(pk.flash_attention(q, k, v, causal=causal)
                       .astype(jnp.float32))

    dense = jax.jit(jax.grad(dense_loss, argnums=(0, 1, 2)))
    flash = jax.jit(jax.grad(flash_loss, argnums=(0, 1, 2)))
    dms = _time(dense, q, k, v)
    fms = _time(flash, q, k, v)
    # flush per line: a timeout-kill (tunnel wedge) must not discard
    # measurements already completed (BENCH_LOG persistence contract)
    print(json.dumps({
        "kernel": "flash_attention_fwd_bwd", "dense_ms": round(dms, 3),
        "fused_ms": round(fms, 3), "speedup": round(dms / fms, 3),
        "shape": [b, t, h, d], "causal": causal, "dtype": dtype,
        "device": str(jax.devices()[0])}), flush=True)


def bench_softmax_xent(n=8192, v=32000):
    jax = _await()
    import jax.numpy as jnp
    from paddle_tpu.ops import pallas_kernels as pk

    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(n, v).astype("f"))
    labels = jnp.asarray(rng.randint(0, v, n).astype("i4"))

    def dense(logits, labels):
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.sum(jnp.take_along_axis(logp, labels[:, None], 1))

    def fused(logits, labels):
        return jnp.sum(pk.softmax_xent(logits, labels))

    d = jax.jit(jax.grad(dense))
    f = jax.jit(jax.grad(fused))
    dms = _time(d, logits, labels)
    fms = _time(f, logits, labels)
    print(json.dumps({
        "kernel": "softmax_xent_fwd_bwd", "dense_ms": round(dms, 3),
        "fused_ms": round(fms, 3), "speedup": round(dms / fms, 3),
        "shape": [n, v], "device": str(jax.devices()[0])}), flush=True)


def tune_attention_blocks(b=8, t=2048, h=8, d=64, causal=True,
                          dtype="bfloat16"):
    """Sweep flash block_q/block_k against the dense baseline, timing the
    forward alone and fwd+bwd separately (the r4 microbench measured
    fwd+bwd at 0.75x dense with the 128/128 default — this isolates
    whether the forward tiling or the backward kernel is the regression)."""
    jax = _await()
    import jax.numpy as jnp
    from paddle_tpu.ops import pallas_kernels as pk

    q, k, v, dense_fwd, dense_loss = _attention_setup(b, t, h, d, causal,
                                                      dtype)
    dense_f = jax.jit(dense_fwd)
    dense_g = jax.jit(jax.grad(dense_loss, argnums=(0, 1, 2)))
    dfms = _time(dense_f, q, k, v)
    dgms = _time(dense_g, q, k, v)
    print(json.dumps({"kernel": "attention_dense_baseline",
                      "fwd_ms": round(dfms, 3), "fwdbwd_ms": round(dgms, 3),
                      "shape": [b, t, h, d], "causal": causal,
                      "device": str(jax.devices()[0])}), flush=True)

    for bq in (128, 256, 512):
        for bk in (128, 256, 512):
            if bq > t or bk > t:
                continue

            def flash_loss(q, k, v, bq=bq, bk=bk):
                return jnp.sum(pk.flash_attention(
                    q, k, v, causal=causal, block_q=bq, block_k=bk)
                    .astype(jnp.float32))

            # fwd and fwd+bwd fail independently (e.g. a block config
            # whose backward kernel exceeds VMEM) — time them separately
            # so a bwd failure cannot discard a banked fwd number
            err = None
            try:
                ff = jax.jit(lambda q, k, v, bq=bq, bk=bk:
                             pk.flash_attention(q, k, v, causal=causal,
                                                block_q=bq, block_k=bk))
                ffms = _time(ff, q, k, v)
            except Exception as e:  # noqa: BLE001 — record, keep sweeping
                ffms = None
                err = "fwd: " + str(e)[:140]
            try:
                fg = jax.jit(jax.grad(flash_loss, argnums=(0, 1, 2)))
                fgms = _time(fg, q, k, v)
            except Exception as e:  # noqa: BLE001
                fgms = None
                err = (err + "; " if err else "") + "bwd: " + str(e)[:140]
            print(json.dumps({
                "kernel": "flash_tune", "block_q": bq, "block_k": bk,
                "fwd_ms": ffms and round(ffms, 3),
                "fwdbwd_ms": fgms and round(fgms, 3),
                "fwd_speedup": ffms and round(dfms / ffms, 3),
                "fwdbwd_speedup": fgms and round(dgms / fgms, 3),
                "error": err}), flush=True)


if __name__ == "__main__":
    # MB_* knobs shrink the config for smoke runs (CPU interpret mode is
    # orders of magnitude slower than the real kernel)
    if os.environ.get("MB_TUNE") == "1":
        tune_attention_blocks(b=int(os.environ.get("MB_B", "8")),
                              t=int(os.environ.get("MB_SEQ", "2048")),
                              h=int(os.environ.get("MB_H", "8")))
    elif os.environ.get("MB_SHAPES"):
        # MB_SHAPES=BxTxHxD[,BxTxHxD...]: attention fwd+bwd comparison
        # at each shape (one line per shape, cheapest-first ordering is
        # the caller's job)
        for spec in os.environ["MB_SHAPES"].split(","):
            b, t, h, d = (int(x) for x in spec.strip().split("x"))
            bench_attention(b=b, t=t, h=h, d=d)
    else:
        bench_attention(b=int(os.environ.get("MB_B", "8")),
                        t=int(os.environ.get("MB_SEQ", "2048")),
                        h=int(os.environ.get("MB_H", "8")))
        bench_softmax_xent(n=int(os.environ.get("MB_N", "8192")),
                           v=int(os.environ.get("MB_V", "32000")))
