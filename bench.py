"""Headline benchmark: ResNet-50 ImageNet training throughput on TPU.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": "images/sec/chip",
"vs_baseline": N}. Baseline = 300 images/sec/chip (Paddle Fluid on V100,
fp32, the era's published ResNet-50 number — BASELINE.json north star says
"≥ Paddle's own V100 images/sec/chip").

Runs on whatever accelerator jax exposes (the axon TPU v5e chip in this
image); synthetic data, full training step (fwd + bwd + momentum update).
"""
import json
import os
import sys
import time

import numpy as np


def main():
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.models.image_classification import build_train

    batch = int(os.environ.get("BENCH_BATCH", "256"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    warmup = int(os.environ.get("BENCH_WARMUP", "5"))
    dtype = os.environ.get("BENCH_DTYPE", "bf16")  # bf16 | fp32
    remat = os.environ.get("BENCH_REMAT", "0") == "1"

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main_prog, startup):
        image, label, avg_cost, acc = build_train(
            model="resnet50", class_dim=1000, image_shape=(3, 224, 224),
            learning_rate=0.1, momentum=0.9, use_bf16=(dtype == "bf16"))
    if remat:  # trade FLOPs for activation memory (enables larger batch)
        fluid.memory_optimization_transpiler.enable_rematerialization(
            main_prog)

    place = fluid.TPUPlace()
    exe = fluid.Executor(place)
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    # one-time host→device transfer; the timed loop feeds device-resident
    # arrays (a real input pipeline would double-buffer the same way)
    import jax.numpy as jnp
    xs = jnp.asarray(rng.rand(batch, 3, 224, 224).astype("float32"))
    ys = jnp.asarray(rng.randint(0, 1000, (batch, 1)).astype("int32"))
    jax.block_until_ready((xs, ys))

    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(warmup):
            loss, = exe.run(main_prog, feed={"image": xs, "label": ys},
                            fetch_list=[avg_cost])
        assert np.isfinite(loss).all(), "non-finite loss in warmup"
        t0 = time.perf_counter()
        for _ in range(steps):
            out = exe.run(main_prog, feed={"image": xs, "label": ys},
                          fetch_list=[avg_cost], return_numpy=False)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0

    ips = batch * steps / dt
    print(json.dumps({
        "metric": "resnet50_imagenet_train_throughput",
        "value": round(ips, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(ips / 300.0, 3),
        "batch": batch,
        "dtype": dtype,
        "device": str(jax.devices()[0]),
        "loss": float(np.asarray(loss).reshape(-1)[0]),
    }))


if __name__ == "__main__":
    main()
