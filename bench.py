"""Headline benchmark: ResNet-50 ImageNet training throughput on TPU.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": "images/sec/chip",
"vs_baseline": N}. Baseline = 300 images/sec/chip (Paddle Fluid on V100,
fp32, the era's published ResNet-50 number — BASELINE.json north star says
"≥ Paddle's own V100 images/sec/chip").

Runs on whatever accelerator jax exposes (the axon TPU v5e chip in this
image); synthetic data, full training step (fwd + bwd + momentum update).
"""
import json
import os
import sys
import time

import numpy as np


def _emit(rec):
    """Every record line — success AND error placeholder — goes out
    through here: schema-checked against paddle_tpu.benchd.schema (the
    store/gate contract, ARCHITECTURE.md §28) so a malformed leg is a
    loud tier-1 failure, not a silently unreadable store entry."""
    from paddle_tpu.benchd.schema import check_record
    print(json.dumps(check_record(rec)))


def _error_line(msg):
    """The one-JSON-line error payload, with the SAME metric/unit mapping
    as the success paths so downstream aggregators keyed on metric names
    bucket error lines correctly."""
    if os.environ.get("BENCH_SERVING") == "1":
        return {"metric": "serving_throughput", "value": 0.0,
                "unit": "requests/sec/chip", "vs_baseline": None,
                "error": msg}
    if os.environ.get("BENCH_POOL") == "1":
        return {"metric": "serving_pool_throughput", "value": 0.0,
                "unit": "requests/sec/chip", "vs_baseline": None,
                "error": msg}
    if os.environ.get("BENCH_FLEET") == "1":
        return {"metric": "serving_fleet_autoscale_qps", "value": 0.0,
                "unit": "requests/sec/chip", "vs_baseline": None,
                "error": msg}
    if os.environ.get("BENCH_CKPT") == "1":
        return {"metric": "ckpt_async_steps_per_sec", "value": 0.0,
                "unit": "steps/sec", "vs_baseline": None, "error": msg}
    if os.environ.get("BENCH_RESIL") == "1":
        return {"metric": "resil_guarded_steps_per_sec", "value": 0.0,
                "unit": "steps/sec", "vs_baseline": None, "error": msg}
    if os.environ.get("BENCH_SENTINEL") == "1":
        return {"metric": "sentinel_steps_per_sec", "value": 0.0,
                "unit": "steps/sec", "vs_baseline": None, "error": msg}
    if os.environ.get("BENCH_COMPILE_CACHE") == "1":
        return {"metric": "compile_cache_serving_warmup", "value": 0.0,
                "unit": "x cold/warm warmup_s", "vs_baseline": None,
                "error": msg}
    if os.environ.get("BENCH_SHARDED") == "1":
        return {"metric": "sharded_update_steps_per_sec", "value": 0.0,
                "unit": "steps/sec", "vs_baseline": None, "error": msg}
    if os.environ.get("BENCH_TP") == "1":
        return {"metric": "tp_train_steps_per_sec", "value": 0.0,
                "unit": "steps/sec", "vs_baseline": None, "error": msg}
    if os.environ.get("BENCH_PIPELINE") == "1":
        return {"metric": "pipeline_dispatch_open_qps", "value": 0.0,
                "unit": "requests/sec/chip", "vs_baseline": None,
                "error": msg}
    if os.environ.get("BENCH_OBS") == "1":
        return {"metric": "observability_overhead", "value": 0.0,
                "unit": "steps/sec/chip", "vs_baseline": None,
                "error": msg}
    if os.environ.get("BENCH_KERNELS") == "1":
        return {"metric": "kernel_floor_speedup", "value": 0.0,
                "unit": "x fused/unfused", "vs_baseline": None,
                "error": msg}
    if os.environ.get("BENCH_DECODE") == "1" \
            and os.environ.get("BENCH_MODEL", "") != "transformer":
        # the standalone continuous-batching leg (BENCH_MODEL=transformer
        # BENCH_DECODE=1 is the older KV-cache beam-decode leg below)
        return {"metric": "decode_continuous_tokens_per_sec", "value": 0.0,
                "unit": "tokens/sec/chip", "vs_baseline": None,
                "error": msg}
    model = os.environ.get("BENCH_MODEL", "resnet50")
    decode = os.environ.get("BENCH_DECODE") == "1"
    token_metric = {"transformer": "transformer_cached_decode_throughput"
                    if decode else "transformer_train_throughput",
                    "stacked_lstm": "stacked_lstm_train_throughput"}
    tok = model in token_metric
    if model == "transformer" and decode:
        unit = "emitted tokens/sec/chip"   # matches the success path
    elif tok:
        unit = "tokens/sec/chip"
    else:
        unit = "images/sec/chip"
    return {"metric": token_metric.get(
                model, "%s_imagenet_train_throughput" % model),
            "value": 0.0,
            "unit": unit,
            "vs_baseline": 0.0 if model == "resnet50" else None,
            "error": msg}


def _await_devices(timeout_s):
    """Device init probe with a watchdog: the axon tunnel can wedge with a
    never-returning claim RPC; better one JSON error line than a silent
    hang past the driver's patience."""
    import threading
    out = {}

    def probe():
        try:
            import jax
            # the axon sitecustomize forces jax_platforms="axon,cpu" in
            # CONFIG regardless of the env var; honor an explicit env
            # request (JAX_PLATFORMS=cpu smoke runs must not touch the
            # tunnel at all)
            want = os.environ.get("JAX_PLATFORMS")
            if want:
                jax.config.update("jax_platforms", want)
            out["devices"] = jax.devices()
        except Exception as e:       # noqa: BLE001 - reported in JSON
            out["error"] = repr(e)

    def fail(msg):
        _emit(_error_line(msg))
        sys.stdout.flush()
        # skip atexit: jax teardown can block on the same wedged runtime
        os._exit(3)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        fail("device init did not return within %ds (TPU tunnel wedged?)"
             % timeout_s)
    if "devices" not in out:
        fail(out.get("error", "device probe thread died without a result"))
    return out["devices"]


def _multistep():
    """BENCH_MULTISTEP=K: run the timed loop through the executors'
    device-resident K-step mode (run(steps=K)) — one host dispatch/sync
    per K training steps instead of per step. K=1 (default) is the plain
    single-step path, byte-identical to the pre-multistep bench."""
    return max(1, int(os.environ.get("BENCH_MULTISTEP", "1")))


def _step_plan(steps, multistep):
    """(outer_calls, total_steps): BENCH_STEPS counts TRAINING steps in
    both modes, rounded up to a whole number of K-step blocks so a
    K-misaligned BENCH_STEPS can't silently measure fewer steps."""
    if multistep == 1:
        return steps, steps
    outer = max(1, -(-steps // multistep))
    return outer, outer * multistep


def _run_kw(multistep):
    """Extra Executor.run kwargs for the timed loop. fetch_reduce='last'
    mirrors what the single-step loop keeps (only the final out survives
    the loop variable), so the loss sanity check sees the same value."""
    return {"steps": multistep, "fetch_reduce": "last"} \
        if multistep > 1 else {}


# bf16 peak TFLOPs per chip by device_kind substring (docs values); the
# device-blind 197 default misreported MFU on anything that isn't a v5e
_PEAK_TFLOPS_BY_KIND = [
    ("v6e", 918.0), ("trillium", 918.0),
    ("v5p", 459.0),
    ("v5e", 197.0), ("v5 lite", 197.0), ("v5litepod", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
]


def _peak_tflops():
    """The MFU denominator: BENCH_PEAK_TFLOPS when set (explicit pin
    wins), else keyed on the actual device_kind so each chip reports
    honest MFU — the old code defaulted to 197 (v5e) regardless of
    hardware. Unknown kinds (incl. the CPU backend) fall back to the
    v5e figure, loudly labeled via the peak_tflops field every bench
    line now carries."""
    env = os.environ.get("BENCH_PEAK_TFLOPS", "")
    if env:
        return float(env)
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 — MFU is decoration, never a crash
        return 197.0
    for sub, peak in _PEAK_TFLOPS_BY_KIND:
        if sub in kind:
            return peak
    return 197.0


def _mfu(flops_per_sec):
    """Model FLOPs utilization against the chip's peak (_peak_tflops:
    keyed on device_kind, BENCH_PEAK_TFLOPS overrides), so every bench
    line self-describes how far it sits from the >=25% north star
    (SURVEY.md section 5)."""
    return round(flops_per_sec / (_peak_tflops() * 1e12), 4)


def bench_transformer():
    """Transformer training throughput through the pallas flash-attention
    path (BENCH_MODEL=transformer). Base-ish config (d_model 512, 8 heads,
    6 layers, seq 256); prints one JSON tokens/sec line (no reference-era
    baseline exists for this metric -> vs_baseline null)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as fluid
    from paddle_tpu.core.utils import device_fetch_barrier
    from paddle_tpu.models import transformer

    batch = int(os.environ.get("BENCH_BATCH", "32"))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "10")))
    warmup = int(os.environ.get("BENCH_WARMUP", "3"))
    seq = int(os.environ.get("BENCH_SEQ", "256"))
    n_layer = int(os.environ.get("BENCH_LAYERS", "6"))
    d_model = int(os.environ.get("BENCH_DMODEL", "512"))
    n_head = int(os.environ.get("BENCH_HEADS", "8"))
    vocab = int(os.environ.get("BENCH_VOCAB", "30000"))
    fused = os.environ.get("BENCH_FUSED_ATTN", "1") == "1"
    fused_qkv = os.environ.get("BENCH_FUSED_QKV", "0") == "1"
    dtype = os.environ.get("BENCH_DTYPE", "bf16")

    main_prog, startup = fluid.Program(), fluid.Program()
    if dtype == "bf16":
        main_prog.enable_mixed_precision()
    with fluid.unique_name.guard(), fluid.program_guard(main_prog, startup):
        sum_cost, avg_cost, _ = transformer.build_train(
            src_vocab_size=vocab, trg_vocab_size=vocab, max_length=seq,
            n_layer=n_layer, n_head=n_head, d_key=d_model // n_head,
            d_value=d_model // n_head, d_model=d_model,
            d_inner_hid=d_model * 4, label_smooth_eps=0.1,
            use_fused_attention=fused, use_qkv_fusion=fused_qkv)

    rng = np.random.RandomState(0)
    srcs = [rng.randint(3, vocab, seq).tolist() for _ in range(batch)]
    feed = transformer.prepare_batch(srcs, srcs, seq, n_head, fused=fused)
    feed = {k: jnp.asarray(v) for k, v in feed.items()}
    jax.block_until_ready(list(feed.values()))

    multistep = _multistep()
    outer, total_steps = _step_plan(steps, multistep)
    run_kw = _run_kw(multistep)
    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(warmup):
            exe.run(main_prog, feed=feed, fetch_list=[avg_cost], **run_kw)
        t0 = time.perf_counter()
        for _ in range(outer):
            out = exe.run(main_prog, feed=feed, fetch_list=[avg_cost],
                          return_numpy=False, **run_kw)
        device_fetch_barrier(out)
        dt = time.perf_counter() - t0
        loss = np.asarray(out[0])
        assert np.isfinite(loss).all(), "non-finite loss"

    tps = batch * seq * total_steps / dt
    # training FLOPs/token ~ 6 * params (72*L*d^2 with d_inner=4d) plus
    # the attention matmuls (~12*L*seq*d fwd+bwd) plus the vocab
    # projection (6*d*V — at base config it rivals the whole body:
    # 92M vs 113M FLOPs/token; omitting it undercounted MFU pre-round-4)
    flops_per_token = 72.0 * n_layer * d_model ** 2 \
        + 12.0 * n_layer * seq * d_model \
        + 6.0 * d_model * vocab
    _emit({
        "metric": "transformer_train_throughput",
        "value": round(tps, 1), "unit": "tokens/sec/chip",
        "vs_baseline": None, "batch": batch, "seq": seq,
        "multistep": multistep,
        "layers": n_layer, "d_model": d_model, "dtype": dtype,
        "fused_attention": fused, "fused_qkv": fused_qkv,
        "device": str(jax.devices()[0]),
        "mfu": _mfu(tps * flops_per_token),
        "peak_tflops": _peak_tflops(),
        "loss": float(loss.reshape(-1)[0])})


def bench_transformer_decode():
    """KV-cache incremental beam decode throughput (BENCH_MODEL=transformer
    BENCH_DECODE=1): tokens generated per second through
    build_cached_decode's while_loop (caches as carries, O(T) decoder
    work). The reference era re-ran the decoder on the growing prefix per
    step; this metric is the TPU-native serving headline."""
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.core.utils import device_fetch_barrier
    from paddle_tpu.models import transformer

    batch = int(os.environ.get("BENCH_BATCH", "16"))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "5")))
    warmup = int(os.environ.get("BENCH_WARMUP", "2"))
    seq = int(os.environ.get("BENCH_SEQ", "128"))
    n_layer = int(os.environ.get("BENCH_LAYERS", "6"))
    d_model = int(os.environ.get("BENCH_DMODEL", "512"))
    n_head = int(os.environ.get("BENCH_HEADS", "8"))
    vocab = int(os.environ.get("BENCH_VOCAB", "30000"))
    beam = int(os.environ.get("BENCH_BEAM", "4"))

    prog, startup = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard(), fluid.program_guard(prog, startup):
        ids, scores = transformer.build_cached_decode(
            src_vocab_size=vocab, trg_vocab_size=vocab, max_length=seq,
            n_layer=n_layer, n_head=n_head, d_key=d_model // n_head,
            d_value=d_model // n_head, d_model=d_model,
            d_inner_hid=d_model * 4, beam_size=beam)

    rng = np.random.RandomState(0)
    srcs = [rng.randint(3, vocab, seq - 2).tolist() for _ in range(batch)]
    feed = transformer.prepare_cached_decode_batch(srcs, seq, n_head, beam)

    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(warmup):
            exe.run(prog, feed=feed, fetch_list=[ids])
        t0 = time.perf_counter()
        for _ in range(steps):
            out = exe.run(prog, feed=feed, fetch_list=[ids],
                          return_numpy=False)
        device_fetch_barrier(out)
        dt = time.perf_counter() - t0

    # Throughput of EMITTED tokens (the returned hypotheses): each run
    # decodes seq-1 positions per batch element. The decoder also scores
    # beam-1 discarded hypotheses per step — that work is real but its
    # tokens are not output, so counting them would inflate tokens/sec
    # (ADVICE r4 #4); beam is in the JSON for FLOP reconstruction.
    tps = batch * (seq - 1) * steps / dt
    _emit({
        "metric": "transformer_cached_decode_throughput",
        "value": round(tps, 1), "unit": "emitted tokens/sec/chip",
        "vs_baseline": None, "batch": batch, "beam": beam, "seq": seq,
        "layers": n_layer, "d_model": d_model,
        "device": str(jax.devices()[0])})


def bench_stacked_lstm():
    """Stacked dynamic-LSTM sentiment training (the reference benchmark
    suite's stacked_dynamic_lstm.py workload): embedding -> 3x (fc+lstm)
    -> pools -> fc. One JSON tokens/sec line."""
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.core.lod import LoDTensor
    from paddle_tpu.core.utils import device_fetch_barrier
    from paddle_tpu.models.understand_sentiment import stacked_lstm_net

    batch = int(os.environ.get("BENCH_BATCH", "128"))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "10")))
    warmup = int(os.environ.get("BENCH_WARMUP", "3"))
    seq = int(os.environ.get("BENCH_SEQ", "64"))
    vocab = int(os.environ.get("BENCH_VOCAB", "10000"))
    hid = int(os.environ.get("BENCH_HIDDEN", "512"))
    stacked = int(os.environ.get("BENCH_LAYERS", "3"))
    dtype = os.environ.get("BENCH_DTYPE", "bf16")

    main_prog, startup = fluid.Program(), fluid.Program()
    if dtype == "bf16":
        main_prog.enable_mixed_precision()
    with fluid.unique_name.guard(), fluid.program_guard(main_prog, startup):
        data = fluid.layers.data(name="words", shape=[1], dtype="int64",
                                 lod_level=1)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        pred = stacked_lstm_net(
            data, dict_dim=vocab, class_dim=2, emb_dim=hid, hid_dim=hid,
            stacked_num=stacked)
        cost = fluid.layers.mean(
            x=fluid.layers.cross_entropy(input=pred, label=label))
        fluid.optimizer.Adam(learning_rate=0.002).minimize(cost)

    rng = np.random.RandomState(0)
    seqs = [rng.randint(1, vocab, (seq, 1)).astype("int64")
            for _ in range(batch)]
    feed = {"words": LoDTensor.from_sequences(seqs),
            "label": rng.randint(0, 2, (batch, 1)).astype("int64")}

    multistep = _multistep()
    outer, total_steps = _step_plan(steps, multistep)
    run_kw = _run_kw(multistep)
    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(warmup):
            exe.run(main_prog, feed=feed, fetch_list=[cost], **run_kw)
        t0 = time.perf_counter()
        for _ in range(outer):
            out = exe.run(main_prog, feed=feed, fetch_list=[cost],
                          return_numpy=False, **run_kw)
        device_fetch_barrier(out)
        dt = time.perf_counter() - t0
        loss = np.asarray(out[0])
        assert np.isfinite(loss).all(), "non-finite loss"

    tps = batch * seq * total_steps / dt
    # fluid packing: dynamic_lstm(size=hid) has hidden width h = hid/4.
    # fwd FLOPs/token: layer 1 fc [emb=4h -> 4h] + recurrent [h, 4h]
    # = 2*4h*(4h+h) = 40h^2; layers >=2 take concat [4h+h -> 4h] + rec
    # = 48h^2. train ~ 3x fwd. (The first cut of this formula assumed
    # hidden == hid and overcounted MFU ~6x.)
    h = hid // 4
    flops_per_token = 3 * (40.0 * h * h + (stacked - 1) * 48.0 * h * h)
    _emit({
        "metric": "stacked_lstm_train_throughput",
        "value": round(tps, 1), "unit": "tokens/sec/chip",
        "vs_baseline": None, "batch": batch, "seq": seq,
        "multistep": multistep,
        "hidden": hid, "stacked": stacked, "dtype": dtype,
        "device": str(jax.devices()[0]),
        "mfu": _mfu(tps * flops_per_token),
        "peak_tflops": _peak_tflops(),
        "loss": float(loss.reshape(-1)[0])})


def _lat_ms(latencies, q):
    """Nearest-rank percentile of a latency list, in ms (the SAME
    percentile the serving /metrics endpoint reports — one definition)."""
    from paddle_tpu.serving.metrics import _percentile
    return round(_percentile(sorted(latencies), q) * 1e3, 3)


def bench_serving():
    """BENCH_SERVING=1: the online-inference leg (paddle_tpu/serving).
    Saves a small MLP via save_inference_model, loads it into an
    InferenceEngine (bucket warmup included), then measures

      * serial baseline — the same requests one at a time, batch=1,
        direct Executor.run (what serving WITHOUT a batcher would do),
      * closed loop — BENCH_SERVING_CLIENTS threads, each firing its next
        request when the previous completes,
      * open loop — a FIXED arrival schedule computed up front (i/rate
        offsets; no wall-clock dependence in what gets dispatched), rate
        BENCH_SERVING_ARRIVAL_QPS (default 2x the serial baseline).

    One JSON line: requests/sec (closed loop) as the headline value plus
    open-loop qps, the serial baseline, latency percentiles and mean
    batch occupancy. The coalescing win is value/serial_qps."""
    import threading

    import jax
    import paddle_tpu as fluid
    from paddle_tpu import serving

    # clients >= max_batch by default so the closed loop can FILL a batch
    # (a full batch dispatches immediately; a partial one waits out
    # max_delay — with fewer clients than batch rows every cycle pays the
    # full coalescing delay and throughput can't beat serial)
    n_requests = int(os.environ.get("BENCH_SERVING_REQUESTS", "256"))
    n_clients = int(os.environ.get("BENCH_SERVING_CLIENTS", "16"))
    max_batch = int(os.environ.get("BENCH_SERVING_MAX_BATCH", "8"))
    max_delay = float(os.environ.get("BENCH_SERVING_MAX_DELAY_MS", "5"))
    feat = int(os.environ.get("BENCH_SERVING_FEATURES", "64"))
    hidden = int(os.environ.get("BENCH_SERVING_HIDDEN", "256"))
    # depth sets the DISPATCH cost (kernels per jitted call) — the fixed
    # per-call overhead batching amortizes; per-row compute stays small.
    # A 2-layer toy on CPU is so dispatch-light that python queueing
    # overhead rivals it and the coalescing win drowns in host noise.
    n_layers = int(os.environ.get("BENCH_SERVING_LAYERS", "4"))
    n_serial = min(n_requests, int(os.environ.get("BENCH_SERVING_SERIAL",
                                                  "64")))

    import tempfile
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main_prog, startup):
        x = fluid.layers.data(name="x", shape=[feat], dtype="float32")
        h = x
        for _ in range(n_layers):
            h = fluid.layers.fc(input=h, size=hidden, act="relu")
        pred = fluid.layers.fc(input=h, size=10, act="softmax")
    exe = fluid.Executor(fluid.TPUPlace())
    model_dir = tempfile.mkdtemp(prefix="ptpu_bench_serving_")
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        fluid.io.save_inference_model(model_dir, ["x"], [pred], exe,
                                      main_prog)

    engine = serving.InferenceEngine(
        model_dir, place=fluid.TPUPlace(), name="bench",
        max_batch_size=max_batch, max_queue_delay_ms=max_delay,
        queue_capacity=max(1024, n_requests))
    import shutil
    shutil.rmtree(model_dir, ignore_errors=True)  # loaded; don't leak
    # a model dir per bench/CI run into the temp dir
    rng = np.random.RandomState(0)
    inputs = [rng.rand(1, feat).astype("float32")
              for _ in range(n_requests)]

    # Loud-honesty rule (same as every other BENCH leg): a request only
    # counts when its result has MATERIALIZED on the host — .numpy() per
    # request, the slice a real client reads. Counting at scatter time
    # would credit enqueue rate (JAX async dispatch) against a serial
    # baseline that pays full execution + D2H, and the coalescing "win"
    # could never lose.

    # serial batch=1 baseline: direct Executor.run per request, no queue
    t0 = time.perf_counter()
    for i in range(n_serial):
        engine.run_direct({"x": inputs[i]}, batch_bucket=1)
    serial_qps = n_serial / (time.perf_counter() - t0)

    # closed loop; latency = client-observed submit -> materialized.
    # A client thread dying silently would SHORTEN the wall clock while
    # the request count stays nominal — inflating the headline — so any
    # client failure fails the whole leg through the _error_line path.
    closed_lat, client_errors, lat_lock = [], [], threading.Lock()
    per_client = n_requests // n_clients

    def client(cid):
        lats = []
        try:
            for i in range(per_client):
                t = time.perf_counter()
                fut = engine.submit({"x": inputs[cid * per_client + i]})
                fut.result(120).numpy()
                lats.append(time.perf_counter() - t)
        except Exception as e:  # noqa: BLE001 - reported as leg failure
            with lat_lock:
                client_errors.append("client %d: %r" % (cid, e))
        with lat_lock:
            closed_lat.extend(lats)

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    closed_dt = time.perf_counter() - t0
    if client_errors:
        engine.close(drain=False)
        _emit(_error_line(
            "serving closed loop: %d client(s) failed: %s"
            % (len(client_errors), "; ".join(client_errors[:3]))))
        sys.stdout.flush()
        os._exit(2)
    closed_qps = (per_client * n_clients) / closed_dt

    # open loop: fixed schedule, rate defaults to 2x the serial baseline
    rate = float(os.environ.get("BENCH_SERVING_ARRIVAL_QPS", "0")) \
        or 2.0 * serial_qps
    schedule = [i / rate for i in range(n_requests)]
    futures, submit_at, open_lat = [], [], []
    t0 = time.perf_counter()
    try:  # same one-JSON-line contract as the closed loop on failure
        for i, offset in enumerate(schedule):
            delay = t0 + offset - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            submit_at.append(time.perf_counter())
            futures.append(engine.submit({"x": inputs[i]}))
        for f, ts in zip(futures, submit_at):
            f.result(120).numpy()
            open_lat.append(time.perf_counter() - ts)
    except Exception as e:  # noqa: BLE001 - reported as leg failure
        engine.close(drain=False)
        _emit(_error_line(
            "serving open loop failed after %d/%d results: %r"
            % (len(open_lat), n_requests, e)))
        sys.stdout.flush()
        os._exit(2)
    open_dt = time.perf_counter() - t0
    open_qps = n_requests / open_dt

    snap = engine.metrics.snapshot()
    engine.close()
    _emit({
        "metric": "serving_throughput",
        "value": round(closed_qps, 1),
        "unit": "requests/sec/chip",
        "vs_baseline": None,
        "serial_qps": round(serial_qps, 1),
        "open_qps": round(open_qps, 1),
        "open_arrival_qps": round(rate, 1),
        "clients": n_clients, "requests": n_requests,
        "max_batch": max_batch, "max_delay_ms": max_delay,
        "layers": n_layers, "hidden": hidden,
        "mean_batch_occupancy": snap["mean_batch_occupancy"],
        "row_utilization": snap["row_utilization"],
        "closed_p50_ms": _lat_ms(closed_lat, 0.50),
        "closed_p95_ms": _lat_ms(closed_lat, 0.95),
        "closed_p99_ms": _lat_ms(closed_lat, 0.99),
        "open_p50_ms": _lat_ms(open_lat, 0.50),
        "open_p95_ms": _lat_ms(open_lat, 0.95),
        "open_p99_ms": _lat_ms(open_lat, 0.99),
        "device": str(jax.devices()[0])})


def bench_decode():
    """BENCH_DECODE=1 (BENCH_MODEL unset): the iteration-level
    continuous-batching decode leg (ARCHITECTURE.md §27). Builds a
    state-carrying decode-step program (greedy argmax feedback through an
    MLP over carried hidden + context rows — the control shape of a
    seq2seq decoder without the transformer bulk), serves it through a
    DecodeEngine, and measures

      * serial baseline — the SAME streams one at a time through a
        solo_clone sharing the engine's weights (decode serving without
        continuous batching). Doubles as the bit-exactness reference.
      * open loop — a FIXED arrival schedule computed up front (i/rate
        offsets), rate BENCH_DECODE_ARRIVAL_QPS streams/sec (default 2x
        the serial baseline), streams admitted into free slots and
        retired at iteration boundaries mid-flight. Mixed per-stream
        token budgets force admits/retires while other streams decode.

    One JSON line: continuous tokens/sec as the headline value plus the
    serial baseline, inter-token p50/p99, mean slot occupancy and
    divergence_vs_solo — HARD-gated: any stream whose token sequence
    differs from its solo decode fails the leg (exit 2). Tokens count
    only when materialized on the host (each iteration host-syncs the
    token row — that sync IS the decode scheduling loop)."""
    import jax
    import paddle_tpu as fluid
    from paddle_tpu import serving

    slots = int(os.environ.get("BENCH_DECODE_SLOTS", "8"))
    n_streams = int(os.environ.get("BENCH_DECODE_STREAMS", "48"))
    base_tokens = int(os.environ.get("BENCH_DECODE_TOKENS", "24"))
    hidden = int(os.environ.get("BENCH_DECODE_HIDDEN", "256"))
    vocab = int(os.environ.get("BENCH_DECODE_VOCAB", "4096"))
    n_layers = int(os.environ.get("BENCH_DECODE_LAYERS", "4"))

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 11
    with fluid.unique_name.guard(), fluid.program_guard(main_prog, startup):
        tok = fluid.layers.create_global_var([slots, 1], 0, "int64",
                                             persistable=True, name="tok")
        h = fluid.layers.create_global_var([slots, hidden], 0.0, "float32",
                                           persistable=True, name="h")
        ctx = fluid.layers.create_global_var([slots, hidden], 0.0,
                                             "float32", persistable=True,
                                             name="ctx")
        z = fluid.layers.concat(
            [fluid.layers.cast(tok, "float32"), h, ctx], axis=1)
        for _ in range(n_layers):
            z = fluid.layers.fc(input=z, size=hidden, act="tanh")
        logits = fluid.layers.fc(input=z, size=vocab)
        nxt = fluid.layers.reshape(
            fluid.layers.argmax(logits, axis=1), shape=[slots, 1])
        fin = fluid.layers.equal(
            nxt, fluid.layers.fill_constant([slots, 1], "int64", 0))
        fluid.layers.assign(nxt, output=tok)
        fluid.layers.assign(z, output=h)

    # mixed budgets: retires happen while other streams keep decoding, so
    # the open loop provably admits INTO a half-full running batch
    budgets = [max(4, base_tokens // 2 + (i * 7) % base_tokens)
               for i in range(n_streams)]
    rng = np.random.RandomState(0)
    feeds = [{"tok": np.array([i % (vocab - 1) + 1], dtype="int64"),
              "ctx": rng.randn(hidden).astype("float32")}
             for i in range(n_streams)]

    engine = serving.DecodeEngine(
        program=main_prog, startup_program=startup,
        token_var=nxt, finished_var=fin, max_slots=slots,
        name="bench-decode", queue_capacity=max(1024, n_streams),
        default_max_new_tokens=base_tokens)

    # serial baseline + bit-exactness reference: one stream at a time
    # through a clone sharing this engine's weights
    solo = engine.solo_clone(name="bench-decode-solo")
    serial_out = []
    t0 = time.perf_counter()
    try:
        for f, budget in zip(feeds, budgets):
            serial_out.append(np.asarray(
                solo.decode(f, max_new_tokens=budget)).reshape(-1))
    except Exception as e:  # noqa: BLE001 - reported as leg failure
        _emit(_error_line(
            "decode serial baseline failed after %d/%d streams: %r"
            % (len(serial_out), n_streams, e)))
        sys.stdout.flush()
        os._exit(2)
    serial_dt = time.perf_counter() - t0
    solo.close()
    serial_tokens = int(sum(len(s) for s in serial_out))
    serial_tps = serial_tokens / serial_dt

    # open loop: fixed schedule, rate defaults to 2x the serial
    # stream-completion rate — pressure enough that slots stay multiply
    # occupied without the pending queue growing unboundedly
    rate = float(os.environ.get("BENCH_DECODE_ARRIVAL_QPS", "0")) \
        or 2.0 * (n_streams / serial_dt)
    schedule = [i / rate for i in range(n_streams)]
    streams, cont_out = [], []
    t0 = time.perf_counter()
    try:
        for i, offset in enumerate(schedule):
            delay = t0 + offset - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            streams.append(engine.submit(feeds[i],
                                         max_new_tokens=budgets[i]))
        for s in streams:
            cont_out.append(np.asarray(s.result(300)).reshape(-1))
    except Exception as e:  # noqa: BLE001 - reported as leg failure
        engine.close(drain=False)
        _emit(_error_line(
            "decode open loop failed after %d/%d streams: %r"
            % (len(cont_out), n_streams, e)))
        sys.stdout.flush()
        os._exit(2)
    cont_dt = time.perf_counter() - t0
    cont_tokens = int(sum(len(s) for s in cont_out))
    stats = engine.decode_stats()
    engine.close()

    mismatched = [i for i, (a, b) in enumerate(zip(cont_out, serial_out))
                  if a.shape != b.shape or not np.array_equal(a, b)]
    divergence = len(mismatched) / float(n_streams)
    if mismatched:  # the per-stream bit-exactness contract is the POINT
        _emit(_error_line(
            "continuous decode diverged from solo on %d/%d streams "
            "(first: stream %d)" % (len(mismatched), n_streams,
                                    mismatched[0])))
        sys.stdout.flush()
        os._exit(2)

    _emit({
        "metric": "decode_continuous_tokens_per_sec",
        "value": round(cont_tokens / cont_dt, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": None,
        "serial_tokens_per_s": round(serial_tps, 1),
        "speedup_vs_serial": round((cont_tokens / cont_dt) / serial_tps, 2),
        "divergence_vs_solo": divergence,
        "streams": n_streams, "slots": slots,
        "tokens": cont_tokens,
        "open_arrival_streams_per_s": round(rate, 2),
        "mean_slot_occupancy": stats["mean_slot_occupancy"],
        "inter_token_p50_ms": stats["inter_token_p50_ms"],
        "inter_token_p99_ms": stats["inter_token_p99_ms"],
        "iterations": stats["iterations"],
        "layers": n_layers, "hidden": hidden, "vocab": vocab,
        "device": str(jax.devices()[0])})


def bench_pipeline():
    """BENCH_PIPELINE=1: pipelined dispatch vs the serial paths, both
    runtimes (ARCHITECTURE.md §22).

    Serving: the deep-and-narrow MLP served twice through the SAME
    fixed open-loop arrival schedule — once with the serial PR-3
    batcher (pipeline_depth=0), once with continuous batching
    (pipeline_depth=BENCH_PIPELINE_DEPTH, default 2). Headline: open-
    loop qps + p50/p99 at fixed load; per leg, ~16 COALESCED results
    (through the real submit path) are compared against run_direct at
    each request's recorded bucket — that max divergence gates
    bit-equality at 0.0.

    Training: a host-io-bound trainer (wide reader records, narrow
    model — the prepass' pop+pad+H2D rivals the device step) run to EOF
    twice from IDENTICAL init: serial prepass vs prefetch=True.
    Headline: steps/s both legs; final params gate bit-equality.
    Epoch 1 warms the compile caches untimed; epoch 2 is measured.

    Knobs: BENCH_PIPELINE_DEPTH, BENCH_PIPELINE_ARRIVAL_QPS (default
    1.2x the measured serial batch=1 capacity — between the two legs'
    sustainable rates on overlapping hardware), BENCH_PIPELINE_REQUESTS,
    BENCH_SERVING_MAX_BATCH/FEATURES/HIDDEN/LAYERS (serving model),
    BENCH_PIPELINE_RECORDS/BATCH/FEAT/HIDDEN/TLAYERS/K (trainer).
    Loud-honesty rules as everywhere: requests/steps count only when
    materialized; any client error fails the leg."""
    import shutil
    import tempfile
    import threading

    import jax
    import paddle_tpu as fluid
    from paddle_tpu import serving
    from paddle_tpu.core.readers import EOFException, ReaderBase

    depth = int(os.environ.get("BENCH_PIPELINE_DEPTH", "2"))
    n_requests = int(os.environ.get("BENCH_PIPELINE_REQUESTS", "192"))
    max_batch = int(os.environ.get("BENCH_SERVING_MAX_BATCH", "8"))
    max_delay = float(os.environ.get("BENCH_SERVING_MAX_DELAY_MS", "5"))
    feat = int(os.environ.get("BENCH_SERVING_FEATURES", "64"))
    hidden = int(os.environ.get("BENCH_SERVING_HIDDEN", "256"))
    n_layers = int(os.environ.get("BENCH_SERVING_LAYERS", "4"))

    # --- the serving model (same deep-and-narrow family as
    # bench_serving: dispatch-bound, so per-batch host work is the cost
    # the pipeline hides) -------------------------------------------------
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main_prog,
                                                        startup):
        x = fluid.layers.data(name="x", shape=[feat], dtype="float32")
        h = x
        for _ in range(n_layers):
            h = fluid.layers.fc(input=h, size=hidden, act="relu")
        pred = fluid.layers.fc(input=h, size=10, act="softmax")
    exe = fluid.Executor(fluid.TPUPlace())
    model_dir = tempfile.mkdtemp(prefix="ptpu_bench_pipeline_")
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        fluid.io.save_inference_model(model_dir, ["x"], [pred], exe,
                                      main_prog)

    rng = np.random.RandomState(0)
    inputs = [rng.rand(1, feat).astype("float32")
              for _ in range(n_requests)]

    def serve_leg(pipeline_depth, rate):
        """One open-loop pass over the fixed schedule; returns
        (qps, lat list, max divergence of COALESCED results vs
        run_direct at each sampled request's recorded bucket — the gate
        must go through the batcher's submit path, not compare two
        run_direct calls that bypass the machinery under test). Any
        client error fails the whole bench with one JSON error line."""
        engine = serving.InferenceEngine(
            model_dir, place=fluid.TPUPlace(), name="pipe%d" %
            pipeline_depth, max_batch_size=max_batch,
            max_queue_delay_ms=max_delay,
            queue_capacity=max(1024, n_requests),
            pipeline_depth=pipeline_depth)
        try:
            schedule = [i / rate for i in range(n_requests)]
            futures, submit_at, lats = [], [], []
            sampled = {}  # req idx -> (outputs, bucket) off the batcher
            sample_every = max(1, n_requests // 16)
            t0 = time.perf_counter()
            for i, offset in enumerate(schedule):
                delay = t0 + offset - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                submit_at.append(time.perf_counter())
                futures.append(engine.submit({"x": inputs[i]}))
            for i, (f, ts) in enumerate(zip(futures, submit_at)):
                out = f.result(120).numpy()   # materialized = counted
                lats.append(time.perf_counter() - ts)
                if i % sample_every == 0:
                    sampled[i] = (out, f.bucket)
            dt = time.perf_counter() - t0
            div = 0.0
            for i, (out, bucket) in sampled.items():
                ref, _ = engine.run_direct({"x": inputs[i]},
                                           batch_bucket=bucket[0],
                                           seq_bucket=bucket[1])
                for k in ref:
                    div = max(div, float(np.max(np.abs(
                        np.asarray(out[k], dtype="f8")
                        - np.asarray(ref[k], dtype="f8")))))
            return n_requests / dt, lats, div
        finally:
            engine.close()

    try:
        # serial engine measures the baseline rate first (one calibration
        # pass at an arbitrary high rate would skew the comparison, so:
        # a short closed burst through run_direct decides the load). The
        # timer starts AFTER construction + warmup + a couple of primed
        # calls — on real hardware the lattice compile costs seconds
        # while the calibration calls cost milliseconds, and folding it
        # in would underestimate serial capacity by orders of magnitude
        # (the derived load point would then stress neither leg).
        cal_n = min(48, n_requests)
        cal_engine = serving.InferenceEngine(
            model_dir, place=fluid.TPUPlace(), name="cal",
            max_batch_size=max_batch, pipeline_depth=0)
        for i in range(2):
            cal_engine.run_direct({"x": inputs[i]}, batch_bucket=1)
        t0 = time.perf_counter()
        for i in range(cal_n):
            cal_engine.run_direct({"x": inputs[i]}, batch_bucket=1)
        serial_qps = cal_n / (time.perf_counter() - t0)
        cal_engine.close()
        # default load point: 1.2x the serial batch=1 capacity — above
        # what the serial batcher sustains without queue growth, inside
        # what the pipelined batcher absorbs (on hardware where host and
        # device actually overlap), so the p50/p99 gap IS the win. On a
        # single shared core both legs saturate identically — CPU
        # numbers here gate correctness, not speed.
        rate = float(os.environ.get("BENCH_PIPELINE_ARRIVAL_QPS", "0")) \
            or 1.2 * serial_qps
        ser_qps, ser_lat, ser_div = serve_leg(0, rate)
        pipe_qps, pipe_lat, pipe_div = serve_leg(depth, rate)
        serving_div = max(ser_div, pipe_div)
    except Exception as e:  # noqa: BLE001 — one JSON error line
        shutil.rmtree(model_dir, ignore_errors=True)
        _emit(_error_line("serving leg failed: %r" % (e,)))
        sys.stdout.flush()
        os._exit(2)
    shutil.rmtree(model_dir, ignore_errors=True)

    # --- the trainer: host-io-bound (records are WIDE, the model is
    # narrow — pop+pad+H2D per step rivals the device step, which is
    # exactly the work prefetch moves off the dispatch path) -------------
    t_records = int(os.environ.get("BENCH_PIPELINE_RECORDS", "48"))
    t_batch = int(os.environ.get("BENCH_PIPELINE_BATCH", "32"))
    t_feat = int(os.environ.get("BENCH_PIPELINE_FEAT", "2048"))
    t_hidden = int(os.environ.get("BENCH_PIPELINE_HIDDEN", "64"))
    t_layers = int(os.environ.get("BENCH_PIPELINE_TLAYERS", "2"))
    t_k = int(os.environ.get("BENCH_PIPELINE_K", "1"))

    rng = np.random.RandomState(1)
    t_data = [(rng.rand(t_batch, t_feat).astype("float32"),
               rng.rand(t_batch, 1).astype("float32"))
              for _ in range(t_records)]

    def t_reader():
        for rec in t_data:
            yield rec

    tdir = tempfile.mkdtemp(prefix="ptpu_bench_pipeline_t_")
    rio = os.path.join(tdir, "train.recordio")
    fluid.recordio_writer.convert_reader_to_recordio_file(rio, t_reader)

    def build_trainer():
        main, st = fluid.Program(), fluid.Program()
        main.random_seed = 11
        st.random_seed = 11
        with fluid.unique_name.guard(), fluid.program_guard(main, st):
            r = fluid.layers.open_recordio_file(
                rio, shapes=[[-1, t_feat], [-1, 1]],
                dtypes=["float32", "float32"], lod_levels=[0, 0])
            xin, yin = fluid.layers.read_file(r)
            hh = xin
            for _ in range(t_layers):
                hh = fluid.layers.fc(input=hh, size=t_hidden, act="relu")
            pp = fluid.layers.fc(input=hh, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(input=pp, label=yin))
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        return main, st, loss

    def reset_readers(scope):
        outermost = {id(scope.get(n)) for n in scope.names()
                     if isinstance(scope.get(n), ReaderBase)}
        for n in scope.names():
            v = scope.get(n)
            under = getattr(v, "_under", None)
            while under is not None:
                outermost.discard(id(under))
                under = getattr(under, "_under", None)
        for n in scope.names():
            v = scope.get(n)
            if isinstance(v, ReaderBase) and id(v) in outermost:
                v.reset()

    def train_leg(prefetch):
        main, st, loss = build_trainer()
        texe = fluid.Executor(fluid.TPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            texe.run(st)
            # identical init across legs: same seeds, same program build
            def epoch(timed):
                n = 0
                last = None
                t0 = time.perf_counter()
                while True:
                    try:
                        last = texe.run(main, fetch_list=[loss],
                                        steps=t_k, prefetch=prefetch,
                                        return_numpy=False)[0]
                    except EOFException:
                        break
                    n += t_k
                # loud honesty: the epoch ends only when the final
                # fetch (and with it the queued device work) is real
                if last is not None:
                    jax.block_until_ready(last.array)
                return n, time.perf_counter() - t0
            epoch(timed=False)          # warm: compiles + caches
            reset_readers(scope)
            n_steps, dt = epoch(timed=True)
            params = {n: np.asarray(scope.get(n))
                      for n in scope.names()
                      if hasattr(scope.get(n), "dtype")}
        return n_steps / dt, n_steps, params

    try:
        ser_sps, n_steps, ser_params = train_leg(False)
        pre_sps, n_steps2, pre_params = train_leg(True)
        assert n_steps == n_steps2, "legs trained different step counts"
        train_div = max(
            float(np.max(np.abs(ser_params[k].astype("f8")
                                - pre_params[k].astype("f8"))))
            for k in ser_params)
    except Exception as e:  # noqa: BLE001 — one JSON error line
        shutil.rmtree(tdir, ignore_errors=True)
        _emit(_error_line("training leg failed: %r" % (e,)))
        sys.stdout.flush()
        os._exit(2)
    shutil.rmtree(tdir, ignore_errors=True)

    _emit({
        "metric": "pipeline_dispatch_open_qps",
        "value": round(pipe_qps, 1),
        "unit": "requests/sec/chip",
        "vs_baseline": None,
        "pipeline_depth": depth,
        "open_arrival_qps": round(rate, 1),
        "requests": n_requests,
        "serial_open_qps": round(ser_qps, 1),
        "serial_p50_ms": _lat_ms(ser_lat, 0.50),
        "serial_p99_ms": _lat_ms(ser_lat, 0.99),
        "pipelined_p50_ms": _lat_ms(pipe_lat, 0.50),
        "pipelined_p99_ms": _lat_ms(pipe_lat, 0.99),
        "serving_divergence": serving_div,
        "train_steps": n_steps,
        "train_k": t_k,
        "train_record_bytes": int(t_batch * (t_feat + 1) * 4),
        "train_serial_steps_s": round(ser_sps, 2),
        "train_prefetch_steps_s": round(pre_sps, 2),
        "train_speedup": round(pre_sps / ser_sps, 3),
        "train_divergence": train_div,
        "device": str(jax.devices()[0])})


def bench_obs():
    """BENCH_OBS=1: the tracing-overhead gate (ARCHITECTURE.md §24).

    The flight recorder is ALWAYS ON in production, so its cost must be
    provably negligible on both hot loops. Two legs, recorder on vs
    off (trace.set_enabled — the only supported use of the switch):

      * training — a dispatch-bound feed-fed MLP (small device step, so
        the per-step span cost is maximally visible); steps/s per leg.
      * serving — the deep-and-narrow MLP through the depth-2 pipelined
        batcher; closed-loop burst from BENCH_OBS_CLIENTS threads; p99
        per leg.

    Contention discipline (the bench_resil lesson): legs run in
    INTERLEAVED rounds and each leg keeps its BEST round (max steps/s,
    min p99) — a noisy-neighbour stall hits one round, the best drops
    it. One JSON line with both overheads, the span count the on-legs
    recorded (proof the recorder was live), and the profiler snapshot's
    on-dispatch-path sync count (must stay 0 with tracing on — spans
    are host timestamps, never device syncs). Knobs:
    BENCH_OBS_ROUNDS/STEPS/REQUESTS/CLIENTS,
    BENCH_SERVING_MAX_BATCH/FEATURES/HIDDEN/LAYERS."""
    import shutil
    import tempfile
    import threading

    import jax
    import paddle_tpu as fluid
    from paddle_tpu import profiler, serving
    from paddle_tpu.observability import trace

    rounds = int(os.environ.get("BENCH_OBS_ROUNDS", "5"))
    n_steps = int(os.environ.get("BENCH_OBS_STEPS", "60"))
    n_requests = int(os.environ.get("BENCH_OBS_REQUESTS", "64"))
    # fewer clients than max_batch ON PURPOSE: batches never fill, so
    # every request pays the deterministic coalescing window — p99 is
    # then a realistic, stable several-ms number and the on/off delta
    # measures the spans, not scheduler jitter on a microsecond tail
    n_clients = int(os.environ.get("BENCH_OBS_CLIENTS", "4"))
    max_batch = int(os.environ.get("BENCH_SERVING_MAX_BATCH", "8"))
    feat = int(os.environ.get("BENCH_SERVING_FEATURES", "64"))
    hidden = int(os.environ.get("BENCH_SERVING_HIDDEN", "128"))
    n_layers = int(os.environ.get("BENCH_SERVING_LAYERS", "4"))

    profiler.reset_profiler()
    trace.configure(capacity=8192)

    # --- training: the bench_resil-scale deep-narrow smoke MLP — a
    # realistic millisecond-class step (per-step span cost is ~13us of
    # host work; gating it against a degenerate micro-step would
    # measure the ratio of two numbers nothing real ever exhibits) ----
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = 7
    startup.random_seed = 7
    with fluid.unique_name.guard(), fluid.program_guard(main_prog,
                                                        startup):
        x = fluid.layers.data(name="x", shape=[64], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = x
        for _ in range(4):
            h = fluid.layers.fc(input=h, size=128, act="relu")
        p = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=p, label=y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    xb = rng.rand(256, 64).astype("float32")
    feed = {"x": xb, "y": xb[:, :1].copy()}

    def train_round():
        t0 = time.perf_counter()
        for _ in range(n_steps):
            out = exe.run(main_prog, feed=feed, fetch_list=[loss],
                          return_numpy=False)
        jax.block_until_ready(out[0].array)  # honest: work is real
        return n_steps / (time.perf_counter() - t0)

    spans_recorded = 0
    try:
        with fluid.scope_guard(scope):
            exe.run(startup)
            train_round()  # warm: compile outside the measurement
            train_sps = {True: 0.0, False: 0.0}
            for _ in range(rounds):
                for enabled in (True, False):
                    trace.set_enabled(enabled)
                    sps = train_round()
                    train_sps[enabled] = max(train_sps[enabled], sps)
            trace.set_enabled(True)
            spans_recorded = len(trace.dump()["events"])
    except Exception as e:  # noqa: BLE001 — one JSON error line
        trace.set_enabled(True)
        _emit(_error_line("training leg failed: %r" % (e,)))
        sys.stdout.flush()
        os._exit(2)

    # --- serving: pipelined batcher, closed-loop burst -----------------
    sm, sst = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard(), fluid.program_guard(sm, sst):
        sx = fluid.layers.data(name="x", shape=[feat], dtype="float32")
        sh = sx
        for _ in range(n_layers):
            sh = fluid.layers.fc(input=sh, size=hidden, act="relu")
        spred = fluid.layers.fc(input=sh, size=10, act="softmax")
    model_dir = tempfile.mkdtemp(prefix="ptpu_bench_obs_")
    with fluid.scope_guard(fluid.Scope()):
        exe.run(sst)
        fluid.io.save_inference_model(model_dir, ["x"], [spred], exe, sm)
    rng = np.random.RandomState(1)
    inputs = [rng.rand(1, feat).astype("float32")
              for _ in range(n_requests)]

    def serve_round(engine):
        lats = [None] * n_requests
        errors = []
        idx_lock = threading.Lock()
        cursor = {"i": 0}

        def client():
            while True:
                with idx_lock:
                    i = cursor["i"]
                    if i >= n_requests:
                        return
                    cursor["i"] = i + 1
                t0 = time.perf_counter()
                try:
                    engine.submit({"x": inputs[i]}).result(120).numpy()
                except Exception as e:  # noqa: BLE001 — loud below
                    errors.append(e)
                    return
                lats[i] = time.perf_counter() - t0

        threads = [threading.Thread(target=client)
                   for _ in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return _lat_ms(sorted(lats), 0.99)

    try:
        engine = serving.InferenceEngine(
            model_dir, place=fluid.TPUPlace(), name="obs",
            max_batch_size=max_batch, max_queue_delay_ms=5,
            queue_capacity=max(1024, n_requests), pipeline_depth=2)
        try:
            serve_round(engine)  # warm
            p99 = {True: float("inf"), False: float("inf")}
            for _ in range(rounds):
                for enabled in (True, False):
                    trace.set_enabled(enabled)
                    p99[enabled] = min(p99[enabled],
                                       serve_round(engine))
            trace.set_enabled(True)
        finally:
            engine.close()
    except Exception as e:  # noqa: BLE001 — one JSON error line
        trace.set_enabled(True)
        shutil.rmtree(model_dir, ignore_errors=True)
        _emit(_error_line("serving leg failed: %r" % (e,)))
        sys.stdout.flush()
        os._exit(2)
    shutil.rmtree(model_dir, ignore_errors=True)

    snap = profiler.snapshot()  # the machine-readable satellite surface
    train_overhead = (train_sps[False] - train_sps[True]) \
        / max(train_sps[False], 1e-9)
    serving_overhead = (p99[True] - p99[False]) / max(p99[False], 1e-9)
    _emit({
        "metric": "observability_overhead",
        "value": round(train_sps[True], 2),
        "unit": "steps/sec/chip",
        "vs_baseline": None,
        "rounds": rounds,
        "train_steps_per_round": n_steps,
        "train_sps_on": round(train_sps[True], 2),
        "train_sps_off": round(train_sps[False], 2),
        "train_overhead": round(train_overhead, 4),
        "serving_requests": n_requests,
        "serving_p99_on_ms": round(p99[True], 3),
        "serving_p99_off_ms": round(p99[False], 3),
        "serving_overhead": round(serving_overhead, 4),
        "spans_recorded": spans_recorded,
        "sync_on_dispatch": snap["sync_stats"]["on_dispatch_path"],
        "device": str(jax.devices()[0])})


def bench_pool():
    """BENCH_POOL=1: the serving-HA leg (serving/pool.ReplicaPool).
    Saves the deep-and-narrow serving MLP once, then for each replica
    count in BENCH_POOL_REPLICAS (default "1,2,4") drives the SAME
    open-loop arrival schedule through a pool and injects the two
    events the subsystem exists to survive:

      * mid-run replica kill (at 1/3 of the schedule, pools with >1
        replica): a hard `kill_replica` while requests are queued on
        the victim — traffic must redistribute with zero client-visible
        errors,
      * mid-run weight reload (at 2/3): `pool.reload(model_dir)` swaps
        a freshly warmed engine into every replica under load — zero
        dropped requests.

    One JSON line: per-leg qps, p50/p99 client latency, error counts
    (the acceptance number is 0), retries/timeouts, and whether the
    kill/reload fired. Latency = submit -> materialized on the client
    thread (failovers included), the same loud-honesty rule as
    bench_serving. Knobs: BENCH_POOL_REQUESTS, BENCH_POOL_REPLICAS,
    BENCH_POOL_ARRIVAL_QPS (default 1.5x the measured serial qps),
    BENCH_POOL_MAX_BATCH, BENCH_SERVING_LAYERS/HIDDEN/FEATURES."""
    import shutil
    import tempfile
    import threading

    import jax
    import paddle_tpu as fluid
    from paddle_tpu import serving

    n_requests = int(os.environ.get("BENCH_POOL_REQUESTS", "240"))
    replica_counts = [int(r) for r in os.environ.get(
        "BENCH_POOL_REPLICAS", "1,2,4").split(",") if r.strip()]
    max_batch = int(os.environ.get("BENCH_POOL_MAX_BATCH", "8"))
    max_delay = float(os.environ.get("BENCH_POOL_MAX_DELAY_MS", "5"))
    feat = int(os.environ.get("BENCH_SERVING_FEATURES", "64"))
    hidden = int(os.environ.get("BENCH_SERVING_HIDDEN", "64"))
    n_layers = int(os.environ.get("BENCH_SERVING_LAYERS", "10"))

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main_prog,
                                                        startup):
        x = fluid.layers.data(name="x", shape=[feat], dtype="float32")
        h = x
        for _ in range(n_layers):
            h = fluid.layers.fc(input=h, size=hidden, act="relu")
        pred = fluid.layers.fc(input=h, size=10, act="softmax")
    exe = fluid.Executor(fluid.TPUPlace())
    model_dir = tempfile.mkdtemp(prefix="ptpu_bench_pool_")
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        fluid.io.save_inference_model(model_dir, ["x"], [pred], exe,
                                      main_prog)

    rng = np.random.RandomState(0)
    inputs = [rng.rand(1, feat).astype("float32")
              for _ in range(n_requests)]

    # serial baseline (sets the open-loop arrival rate)
    probe = serving.InferenceEngine(model_dir, place=fluid.TPUPlace(),
                                    name="pool-probe",
                                    max_batch_size=max_batch,
                                    max_queue_delay_ms=max_delay)
    t0 = time.perf_counter()
    n_serial = min(48, n_requests)
    for i in range(n_serial):
        probe.run_direct({"x": inputs[i]}, batch_bucket=1)
    serial_qps = n_serial / (time.perf_counter() - t0)
    probe.close()
    rate = float(os.environ.get("BENCH_POOL_ARRIVAL_QPS", "0")) \
        or 1.5 * serial_qps

    legs = {}
    for n_rep in replica_counts:
        pool = serving.ReplicaPool(
            model_dir, replicas=n_rep, name="bench-pool",
            max_batch_size=max_batch, max_queue_delay_ms=max_delay,
            queue_capacity=max(1024, n_requests),
            attempt_timeout_s=30.0, retries=3)
        kill_at = n_requests // 3 if n_rep > 1 else None
        reload_at = (2 * n_requests) // 3
        events, futures, submit_at = [], [], []
        errors, latencies, lat_lock = [], [], threading.Lock()

        def finish(i, fut, ts):
            try:
                fut.result(120).numpy()
                with lat_lock:
                    latencies.append(time.perf_counter() - ts)
            except Exception as e:  # noqa: BLE001 — the error COUNT is
                with lat_lock:      # the leg's acceptance number
                    errors.append("req %d: %r" % (i, e))

        waiters = []
        t0 = time.perf_counter()
        for i in range(n_requests):
            delay = t0 + i / rate - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            if kill_at is not None and i == kill_at:
                pool.kill_replica(n_rep - 1)
                events.append("kill@%d" % i)
            if i == reload_at:
                # reload the SAME weights, CONCURRENTLY with the arrival
                # stream: the event under test is the swap-under-load,
                # and bit-identical weights keep every response
                # comparable. The thread is joined before the leg ends
                # so its completion is part of the measured wall.
                reload_t = threading.Thread(
                    target=pool.reload, kwargs={"model_dir": model_dir})
                reload_t.start()
                waiters.append(reload_t)
                events.append("reload@%d" % i)
            ts = time.perf_counter()
            try:
                fut = pool.submit({"x": inputs[i]})
            except Exception as e:  # noqa: BLE001
                with lat_lock:
                    errors.append("submit %d: %r" % (i, e))
                continue
            w = threading.Thread(target=finish, args=(i, fut, ts))
            w.start()
            waiters.append(w)
        for w in waiters:
            w.join()
        wall = time.perf_counter() - t0
        snap = pool.metrics.snapshot()
        pool.close()
        legs[str(n_rep)] = {
            "qps": round(len(latencies) / wall, 1),
            "p50_ms": _lat_ms(latencies, 0.50),
            "p99_ms": _lat_ms(latencies, 0.99),
            "errors": len(errors),
            "error_samples": errors[:3],
            "completed": len(latencies),
            "retries": snap["retries_total"],
            "attempt_timeouts": snap["attempt_timeouts_total"],
            "events": events,
        }

    shutil.rmtree(model_dir, ignore_errors=True)
    headline = legs[str(replica_counts[-1])]
    _emit({
        "metric": "serving_pool_throughput",
        "value": headline["qps"],
        "unit": "requests/sec/chip",
        "vs_baseline": None,
        "serial_qps": round(serial_qps, 1),
        "arrival_qps": round(rate, 1),
        "requests": n_requests, "max_batch": max_batch,
        "layers": n_layers, "hidden": hidden,
        "legs": legs,
        "total_errors": sum(l["errors"] for l in legs.values()),
        "device": str(jax.devices()[0])})


def bench_fleet():
    """BENCH_FLEET=1: the self-scaling fleet leg (serving/autoscaler).
    One load step, two pools, same closed-loop client schedule:

      * FIXED leg — 1 replica, small queue, autoscale OFF: the load
        step sheds sustained 429s for its whole duration (the
        reference-era fixed-size deployment failure mode).
      * AUTOSCALED leg — the same pool with autoscale [1,
        BENCH_FLEET_MAX_REPLICAS]: the controller grows the pool off
        the shed/queue signals (scale-up latency = engine build +
        warmup, an AOT-cache disk load when the cache is armed) until
        the shedding stops; after the load the pool drains back to 1.

    One JSON line: per-leg qps, total and TAIL-third 429 rates (the
    acceptance number: fixed stays shedding, autoscaled returns to
    ~0), scale-up count + latency, final replica count, client errors
    (must be 0). Clients retry 429s after the server's Retry-After
    hint, so completed counts are comparable across legs. On the
    1-core CPU container extra replicas add queue+admission capacity,
    not compute — qps parity is expected there and the 429-rate drop
    is the measured claim; on TPU the replicas land on distinct chips
    and qps scales too. Knobs: BENCH_FLEET_CLIENTS,
    BENCH_FLEET_SECONDS, BENCH_FLEET_MAX_REPLICAS,
    BENCH_FLEET_QUEUE_CAP, BENCH_SERVING_LAYERS/HIDDEN/FEATURES."""
    import shutil
    import tempfile
    import threading

    import jax
    import paddle_tpu as fluid
    from paddle_tpu import serving

    n_clients = int(os.environ.get("BENCH_FLEET_CLIENTS", "12"))
    seconds = float(os.environ.get("BENCH_FLEET_SECONDS", "3"))
    max_replicas = int(os.environ.get("BENCH_FLEET_MAX_REPLICAS", "3"))
    queue_cap = int(os.environ.get("BENCH_FLEET_QUEUE_CAP", "8"))
    max_batch = int(os.environ.get("BENCH_POOL_MAX_BATCH", "8"))
    feat = int(os.environ.get("BENCH_SERVING_FEATURES", "64"))
    hidden = int(os.environ.get("BENCH_SERVING_HIDDEN", "64"))
    n_layers = int(os.environ.get("BENCH_SERVING_LAYERS", "10"))

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main_prog,
                                                        startup):
        x = fluid.layers.data(name="x", shape=[feat], dtype="float32")
        h = x
        for _ in range(n_layers):
            h = fluid.layers.fc(input=h, size=hidden, act="relu")
        pred = fluid.layers.fc(input=h, size=10, act="softmax")
    exe = fluid.Executor(fluid.TPUPlace())
    model_dir = tempfile.mkdtemp(prefix="ptpu_bench_fleet_")
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        fluid.io.save_inference_model(model_dir, ["x"], [pred], exe,
                                      main_prog)
    rng = np.random.RandomState(0)
    inputs = [rng.rand(1, feat).astype("float32") for _ in range(64)]

    def drive(pool):
        """Closed-loop clients for `seconds`; 429s retried after the
        pool's own Retry-After hint. Returns wall, completions,
        reject timestamps, client errors."""
        t0 = time.perf_counter()
        done, rejects, errors = [], [], []
        lock = threading.Lock()

        def client(ci):
            k = 0
            while time.perf_counter() - t0 < seconds:
                try:
                    pool.submit({"x": inputs[(ci * 7 + k) % 64]}) \
                        .result(60).numpy()
                    with lock:
                        done.append(time.perf_counter() - t0)
                except serving.QueueFullError as e:
                    with lock:
                        rejects.append(time.perf_counter() - t0)
                    time.sleep(min(e.retry_after_s or 0.003, 0.05))
                except Exception as e:  # noqa: BLE001 — the acceptance
                    with lock:          # count is 0
                        errors.append(repr(e))
                k += 1

        threads = [threading.Thread(target=client, args=(ci,))
                   for ci in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0, done, rejects, errors

    def leg_record(wall, done, rejects, errors):
        tail_t = 2.0 * seconds / 3.0
        tail_done = sum(1 for t in done if t >= tail_t)
        tail_rej = sum(1 for t in rejects if t >= tail_t)
        return {
            "qps": round(len(done) / wall, 1),
            "completed": len(done),
            "rejects": len(rejects),
            "reject_rate": round(len(rejects)
                                 / max(len(done) + len(rejects), 1), 4),
            "tail_reject_rate": round(
                tail_rej / max(tail_done + tail_rej, 1), 4),
            "errors": len(errors),
            "error_samples": errors[:3],
        }

    pool_kw = dict(max_batch_size=max_batch, max_queue_delay_ms=2,
                   queue_capacity=queue_cap, attempt_timeout_s=30.0)

    # ---- fixed-size leg: the reference-era deployment, shedding
    fixed_pool = serving.ReplicaPool(model_dir, replicas=1,
                                     name="fleet-fixed", **pool_kw)
    legs = {"fixed": leg_record(*drive(fixed_pool))}
    fixed_pool.close()

    # ---- autoscaled leg: same schedule, the controller absorbs it
    auto_pool = serving.ReplicaPool(
        model_dir, replicas=1, name="fleet-auto", autoscale=True,
        min_replicas=1, max_replicas=max_replicas,
        autoscale_kw=dict(interval_s=0.05, scale_up_cooldown_s=0.2,
                          scale_down_cooldown_s=0.3, down_idle_s=0.5),
        **pool_kw)
    wall, done, rejects, errors = drive(auto_pool)
    scaler = auto_pool._autoscaler
    rec = leg_record(wall, done, rejects, errors)
    rec.update({
        "scale_ups": scaler.scale_ups,
        "scale_up_latency_s": (round(scaler.last_scale_up_s, 3)
                               if scaler.last_scale_up_s is not None
                               else None),
        "peak_replicas": auto_pool.live_replica_count(),
    })
    # contraction: idle drains back to min without failing anything
    t_shrink = time.perf_counter()
    while auto_pool.live_replica_count() > 1 \
            and time.perf_counter() - t_shrink < 30:
        time.sleep(0.1)
    rec["final_replicas"] = auto_pool.live_replica_count()
    rec["scale_downs"] = scaler.scale_downs
    legs["autoscaled"] = rec
    auto_pool.close()
    shutil.rmtree(model_dir, ignore_errors=True)

    _emit({
        "metric": "serving_fleet_autoscale_qps",
        "value": legs["autoscaled"]["qps"],
        "unit": "requests/sec/chip",
        "vs_baseline": None,
        "clients": n_clients, "seconds": seconds,
        "max_replicas": max_replicas, "queue_capacity": queue_cap,
        "layers": n_layers, "hidden": hidden,
        "legs": legs,
        "total_errors": sum(l["errors"] for l in legs.values()),
        "device": str(jax.devices()[0])})


# fwd FLOPs per 224x224 image (2x the usual MACs figure — VGG16's famous
# "15.5G" is MACs, so fwd = 31e9); models build_train supports but this
# table lacks still bench (mfu reported null)
_IMAGE_MODELS = {
    # fwd FLOPs/image at 224^2/1000 classes (train ~ 3x fwd), each
    # MEASURED with XLA cost_analysis on the network AS IMPLEMENTED in
    # models/image_classification.py (is_test forward, 2026-07-31 —
    # same methodology as the r4 resnet50 audit, which also matches
    # per-conv shape sums): resnet50 8.14e9, resnet101 1.541e10,
    # resnet152 2.307e10, vgg16 3.011e10, alexnet (legacy 96-filter
    # unpadded-conv1 ungrouped variant) 1.852e9, googlenet v1 (aux
    # heads off) 2.734e9.
    "resnet50": (3 * 8.2e9, "resnet50_imagenet_train_throughput"),
    "resnet101": (3 * 15.4e9, "resnet101_imagenet_train_throughput"),
    "resnet152": (3 * 23.1e9, "resnet152_imagenet_train_throughput"),
    "vgg16": (3 * 30.1e9, "vgg16_imagenet_train_throughput"),
    "alexnet": (3 * 1.85e9, "alexnet_imagenet_train_throughput"),
    "googlenet": (3 * 2.73e9, "googlenet_imagenet_train_throughput"),
}


def bench_ckpt():
    """BENCH_CKPT=1: checkpointing overhead. Trains the same small Adam
    MLP three ways — no checkpointing, SYNCHRONOUS save every E steps
    (save blocks until the snapshot is published), ASYNC save every E
    steps (capture-only on the training thread, write on the manager's
    background thread) — and reports steps/s plus the training-loop STALL
    each mode paid to checkpointing (time blocked inside save calls) and
    the background save latency. One JSON line; the async-vs-sync stall
    gap is the number the subsystem exists to create.

    Knobs: BENCH_STEPS (timed steps), BENCH_CKPT_EVERY (save period E),
    BENCH_CKPT_DIM (MLP width — scales checkpoint bytes), BENCH_BATCH,
    BENCH_WARMUP."""
    import shutil
    import tempfile

    import jax
    import paddle_tpu as fluid
    from paddle_tpu.checkpoint import CheckpointManager
    from paddle_tpu.core.utils import device_fetch_barrier

    batch = int(os.environ.get("BENCH_BATCH", "32"))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "40")))
    warmup = int(os.environ.get("BENCH_WARMUP", "2"))
    dim = int(os.environ.get("BENCH_CKPT_DIM", "256"))
    every = max(1, int(os.environ.get("BENCH_CKPT_EVERY", "5")))

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main_prog,
                                                        startup):
        x = fluid.layers.data(name="x", shape=[dim], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=dim, act="tanh")
        h = fluid.layers.fc(input=h, size=dim, act="tanh")
        p = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.mean(
            x=fluid.layers.square_error_cost(input=p, label=y))
        # Adam: 2 moments per param — checkpoint bytes ~3x params, the
        # realistic ratio a real trainer snapshots
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)

    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    xs = jnp.asarray(rng.rand(batch, dim).astype("float32"))
    ys = jnp.asarray(rng.rand(batch, 1).astype("float32"))
    jax.block_until_ready((xs, ys))
    feed = {"x": xs, "y": ys}
    exe = fluid.Executor(fluid.TPUPlace())

    results = {}
    for mode in ("none", "sync", "async"):
        ckdir = tempfile.mkdtemp(prefix="bench_ckpt_%s_" % mode)
        scope = fluid.Scope()
        mgr = None
        if mode != "none":
            mgr = CheckpointManager(ckdir, max_to_keep=3,
                                    async_save=(mode == "async"),
                                    max_in_flight=2)
        handles, stall, drain = [], 0.0, 0.0
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(warmup):
                exe.run(main_prog, feed=feed, fetch_list=[loss])
            out = None
            t0 = time.perf_counter()
            for i in range(1, steps + 1):
                out = exe.run(main_prog, feed=feed, fetch_list=[loss],
                              return_numpy=False)
                if mgr is not None and i % every == 0:
                    ts = time.perf_counter()
                    handles.append(mgr.save(i, program=main_prog,
                                            scope=scope,
                                            wait=(mode == "sync")))
                    stall += time.perf_counter() - ts
            device_fetch_barrier(out)
            loop_dt = time.perf_counter() - t0
            if mgr is not None:
                td = time.perf_counter()
                mgr.wait()
                drain = time.perf_counter() - td
                mgr.close()
        writes = [h.write_seconds for h in handles
                  if h.write_seconds is not None]
        results[mode] = {
            "steps_per_sec": round(steps / loop_dt, 2),
            "stall_ms": round(stall * 1e3, 3),
            "drain_ms": round(drain * 1e3, 3),
            "save_latency_ms": round(
                1e3 * sum(writes) / len(writes), 3) if writes else None,
            "saves": len(handles),
        }
        shutil.rmtree(ckdir, ignore_errors=True)

    _emit({
        "metric": "ckpt_async_steps_per_sec",
        "value": results["async"]["steps_per_sec"],
        "unit": "steps/sec",
        "vs_baseline": None,
        "batch": batch, "dim": dim, "steps": steps, "every": every,
        "modes": results,
        "device": str(jax.devices()[0]),
    })


def bench_sharded():
    """BENCH_SHARDED=1: ZeRO-style sharded weight update vs the
    replicated data-parallel baseline (parallel/plan.py,
    ARCHITECTURE.md §21). Trains the same Adam MLP twice on an N-device
    mesh from identical init — replicated update state vs
    `sharded_weight_update=True` — and reports steps/s for both, the
    per-chip update-state bytes each plan's memory accounting prices
    (the 1/N the sharding exists to buy), and the max absolute fetch
    divergence between the two loss streams (must be 0: sharding the
    update never changes the math). One JSON line.

    Knobs: BENCH_STEPS (timed steps), BENCH_WARMUP, BENCH_BATCH (global
    batch, split over the mesh), BENCH_SHARDED_DIM (MLP width — scales
    the update-state bytes), BENCH_SHARDED_DEVICES (mesh size, default
    every visible device)."""
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.core.utils import device_fetch_barrier
    from paddle_tpu.parallel.mesh import make_mesh

    n = int(os.environ.get("BENCH_SHARDED_DEVICES",
                           str(len(jax.devices()))))
    if n < 2:
        _emit(_error_line(
            "BENCH_SHARDED needs a multi-device mesh (%d visible); on "
            "CPU run under XLA_FLAGS=--xla_force_host_platform_device_"
            "count=N" % n))
        sys.stdout.flush()
        os._exit(2)
    batch = int(os.environ.get("BENCH_BATCH", "64"))
    if batch % n:
        batch = ((batch + n - 1) // n) * n  # divisibility contract
    steps = max(1, int(os.environ.get("BENCH_STEPS", "30")))
    warmup = int(os.environ.get("BENCH_WARMUP", "2"))
    dim = int(os.environ.get("BENCH_SHARDED_DIM", "256"))

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = 5
    startup.random_seed = 5
    with fluid.unique_name.guard(), fluid.program_guard(main_prog,
                                                        startup):
        x = fluid.layers.data(name="x", shape=[dim], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=dim, act="tanh")
        h = fluid.layers.fc(input=h, size=dim, act="tanh")
        p = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.mean(
            x=fluid.layers.square_error_cost(input=p, label=y))
        # Adam: the 2-moments-per-param update state the sharding halves
        # per doubling of the mesh — the realistic ZeRO target
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)

    rng = np.random.RandomState(0)
    xs = rng.rand(batch, dim).astype("float32")
    ys = rng.rand(batch, 1).astype("float32")
    feed = {"x": xs, "y": ys}
    mesh = make_mesh({"dp": n}, jax.devices()[:n])
    exe = fluid.Executor(fluid.TPUPlace())

    results, mem, losses = {}, {}, {}
    init = None
    for mode in ("replicated", "sharded"):
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            if init is None:
                # REAL copies, not np.asarray views: on the CPU backend
                # np.asarray of a jax array is zero-copy, and the
                # donated in-place update frees the viewed buffer —
                # the "identical init" would silently mutate under the
                # second leg (found as a warm-compile-cache-only bench
                # failure: cache hits shifted allocator reuse timing)
                init = {nm: np.array(scope.get(nm), copy=True)
                        for nm in scope.names()}
            else:
                for nm, v in init.items():
                    scope.set(nm, v)
            scope._rng_counter = 0
            pexe = fluid.ParallelExecutor(
                main_program=main_prog, loss_name=loss.name, mesh=mesh,
                sharded_weight_update=(mode == "sharded"))
            mem[mode] = pexe.plan.memory_report()
            for _ in range(warmup):
                pexe.run([loss.name], feed=feed)
            handles = []
            t0 = time.perf_counter()
            for _ in range(steps):
                handles.append(pexe.run([loss.name], feed=feed,
                                        return_numpy=False)[0])
            device_fetch_barrier(handles[-1:])
            dt = time.perf_counter() - t0
            # materialize AFTER the clock: the per-step losses feed the
            # divergence check, not the throughput number
            losses[mode] = [float(np.ravel(np.asarray(h))[0])
                            for h in handles]
            results[mode] = round(steps / dt, 2)
            assert all(np.isfinite(v) for v in losses[mode]), \
                "non-finite loss in %s leg" % mode

    divergence = max(abs(a - b) for a, b in
                     zip(losses["replicated"], losses["sharded"]))
    upd_r = mem["replicated"]["update_state"]["per_chip_bytes"]
    upd_s = mem["sharded"]["update_state"]["per_chip_bytes"]
    _emit({
        "metric": "sharded_update_steps_per_sec",
        "value": results["sharded"],
        "unit": "steps/sec",
        "vs_baseline": None,
        "devices": n, "batch": batch, "dim": dim, "steps": steps,
        "replicated_steps_per_sec": results["replicated"],
        "sharded_steps_per_sec": results["sharded"],
        "update_state_bytes_per_chip": {
            "replicated": upd_r, "sharded": upd_s,
            "ratio": round(upd_s / max(upd_r, 1), 4)},
        "params_bytes_per_chip": {
            "replicated": mem["replicated"]["params"]["per_chip_bytes"],
            "sharded": mem["sharded"]["params"]["per_chip_bytes"]},
        "fetch_divergence": divergence,
        "final_loss": losses["sharded"][-1],
        "device": str(jax.devices()[0]),
    })


def bench_tp():
    """BENCH_TP=1: tensor-parallel training as a Plan (parallel/plan.py
    tp_axis, ARCHITECTURE.md §23). Trains the same Adam MLP from
    identical init at mesh-1 and at tp=2/tp=4 ({'dp': 1, 'tp': n}
    meshes, auto row/col per-family specs, gather placement) and
    reports steps/s per leg, the per-chip PARAM bytes each plan's
    memory accounting prices (the 1/tp the intra-layer sharding buys —
    the "bigger than one chip" number), and the max absolute fetch
    divergence of each TP leg against the mesh-1 leg. The gather
    placement's contract is divergence EXACTLY 0.0: weights live
    sharded at rest and all-gather on use, so the math is the
    replicated math (test_bench_tp_smoke gates it). One JSON line.

    Knobs: BENCH_STEPS (timed steps), BENCH_WARMUP, BENCH_BATCH,
    BENCH_TP_DIM (MLP width — scales the at-rest param bytes),
    BENCH_TP_LEGS (comma list of tp sizes, default "1,2,4")."""
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.core.utils import device_fetch_barrier
    from paddle_tpu.parallel.mesh import make_mesh

    legs_cfg = [int(v) for v in
                os.environ.get("BENCH_TP_LEGS", "1,2,4").split(",")]
    if 1 not in legs_cfg:
        legs_cfg = [1] + legs_cfg  # mesh-1 is the divergence baseline
    need = max(legs_cfg)
    if len(jax.devices()) < need:
        _emit(_error_line(
            "BENCH_TP legs %r need %d devices (%d visible); on CPU run "
            "under XLA_FLAGS=--xla_force_host_platform_device_count=N"
            % (legs_cfg, need, len(jax.devices()))))
        sys.stdout.flush()
        os._exit(2)
    batch = int(os.environ.get("BENCH_BATCH", "64"))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "30")))
    warmup = int(os.environ.get("BENCH_WARMUP", "2"))
    dim = int(os.environ.get("BENCH_TP_DIM", "256"))

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = 5
    startup.random_seed = 5
    with fluid.unique_name.guard(), fluid.program_guard(main_prog,
                                                        startup):
        x = fluid.layers.data(name="x", shape=[dim], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=dim, act="tanh")
        h = fluid.layers.fc(input=h, size=dim, act="tanh")
        p = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.mean(
            x=fluid.layers.square_error_cost(input=p, label=y))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)

    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(batch, dim).astype("float32"),
            "y": rng.rand(batch, 1).astype("float32")}
    exe = fluid.Executor(fluid.TPUPlace())

    results, mem, losses = {}, {}, {}
    init = None
    for n in legs_cfg:
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            if init is None:
                # REAL copies (not views of donated buffers — see
                # bench_sharded for the war story)
                init = {nm: np.array(scope.get(nm), copy=True)
                        for nm in scope.names()}
            else:
                for nm, v in init.items():
                    scope.set(nm, v)
            scope._rng_counter = 0
            mesh = make_mesh({"dp": 1, "tp": n}, jax.devices()[:n])
            pexe = fluid.ParallelExecutor(
                main_program=main_prog, loss_name=loss.name, mesh=mesh,
                tp_axis="tp")
            mem[n] = pexe.plan.memory_report()
            for _ in range(warmup):
                pexe.run([loss.name], feed=feed)
            handles = []
            t0 = time.perf_counter()
            for _ in range(steps):
                handles.append(pexe.run([loss.name], feed=feed,
                                        return_numpy=False)[0])
            device_fetch_barrier(handles[-1:])
            dt = time.perf_counter() - t0
            losses[n] = [float(np.ravel(np.asarray(h))[0])
                         for h in handles]
            results[n] = round(steps / dt, 2)
            assert all(np.isfinite(v) for v in losses[n]), \
                "non-finite loss in tp=%d leg" % n

    divergence = max((abs(a - b)
                      for n in legs_cfg if n != 1
                      for a, b in zip(losses[1], losses[n])),
                     default=0.0)
    tp_max = max(legs_cfg)
    par_1 = mem[1]["params"]["replicated_per_chip_bytes"]
    _emit({
        "metric": "tp_train_steps_per_sec",
        "value": results[tp_max],
        "unit": "steps/sec",
        "vs_baseline": None,
        "devices": tp_max, "batch": batch, "dim": dim, "steps": steps,
        "legs": {str(n): {
            "steps_per_sec": results[n],
            "params_bytes_per_chip": mem[n]["params"]["per_chip_bytes"],
            "params_ratio": round(
                mem[n]["params"]["per_chip_bytes"] / max(par_1, 1), 4),
        } for n in legs_cfg},
        "fetch_divergence": divergence,
        "final_loss": losses[tp_max][-1],
        "tp_placement": "gather",
        "device": str(jax.devices()[0]),
    })


def bench_resil():
    """BENCH_RESIL=1: numerical-guard overhead. Trains the deep-narrow
    smoke MLP four ways — guards off/on x single-step/steps=K — and
    reports steps/s for each plus the two overhead percentages. The
    guards add per-grad all-finite reductions (fused into the backward)
    plus ONE lax.cond gating every persistable update; the number this
    leg exists to defend is overhead < 10% on both legs
    (test_bench_resil_smoke asserts it). Batch defaults to 256: guard
    cost is proportional to STATE traffic while step cost scales with
    batch compute, so a degenerate tiny-batch toy would report a
    state/compute ratio no real trainer has.

    Knobs: BENCH_STEPS, BENCH_WARMUP, BENCH_BATCH, BENCH_RESIL_LAYERS,
    BENCH_RESIL_HIDDEN, BENCH_MULTISTEP (K for the multi-step leg),
    BENCH_RESIL_REPEATS (timed rounds; per-leg min taken).

    Deflake discipline (this leg gates a RATIO on a shared CI box):
    the four legs are timed in INTERLEAVED rounds — every round times
    plain/guarded/multi/multi-guarded back-to-back, and each leg keeps
    its min across rounds. A host-contention burst that lands inside
    one round slows every leg of that round together and the min drops
    the whole round, instead of (the old sequential-blocks layout)
    landing entirely inside ONE leg's timing block and inventing
    overhead the guards never had — the tier-1 flake noted in PR 9/10
    verification."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as fluid
    from paddle_tpu.resilience import install_numeric_guards

    batch = int(os.environ.get("BENCH_BATCH", "256"))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "64")))
    warmup = int(os.environ.get("BENCH_WARMUP", "2"))
    n_layers = int(os.environ.get("BENCH_RESIL_LAYERS", "10"))
    hidden = int(os.environ.get("BENCH_RESIL_HIDDEN", "64"))
    k = max(2, int(os.environ.get("BENCH_MULTISTEP", "8")))
    # five rounds by default (was three): the PR-10-era flake analysis
    # showed a single contention burst can survive three mins on a
    # loaded CI box; with five, the min has slack to drop two bad rounds
    repeats = max(1, int(os.environ.get("BENCH_RESIL_REPEATS", "5")))

    rng = np.random.RandomState(0)
    xs = jnp.asarray(rng.rand(batch, hidden).astype("float32"))
    ys = jnp.asarray(rng.rand(batch, 1).astype("float32"))
    jax.block_until_ready((xs, ys))
    feed = {"x": xs, "y": ys}

    def build(guarded):
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.unique_name.guard(), fluid.program_guard(main_prog,
                                                            startup):
            x = fluid.layers.data(name="x", shape=[hidden],
                                  dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            h = x
            for _ in range(n_layers):
                h = fluid.layers.fc(input=h, size=hidden, act="relu")
            p = fluid.layers.fc(input=h, size=1)
            loss = fluid.layers.mean(
                x=fluid.layers.square_error_cost(input=p, label=y))
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
        if guarded:
            install_numeric_guards(main_prog, loss=loss)
        return main_prog, startup, loss

    exe = fluid.Executor(fluid.TPUPlace())

    # build + warm all four legs FIRST (each keeps its own live scope,
    # so training state persists across the interleaved rounds)
    legs = {}
    for name, guarded, multistep in (("plain", False, 1),
                                     ("guarded", True, 1),
                                     ("multi", False, k),
                                     ("multi_guarded", True, k)):
        main_prog, startup, loss = build(guarded)
        run_kw = {"steps": multistep, "fetch_reduce": "last"} \
            if multistep > 1 else {}
        outer = max(1, -(-steps // multistep))
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(warmup):
                exe.run(main_prog, feed=feed, fetch_list=[loss], **run_kw)
        legs[name] = {"prog": main_prog, "loss": loss, "scope": scope,
                      "run_kw": run_kw, "outer": outer,
                      "multistep": multistep, "best": None, "out": None}

    # per-call materialization (return_numpy default): the realistic
    # trainer pattern — a loop that reads its loss every dispatch.
    # Comparing an ASYNC unguarded loop against the guard's mandatory
    # per-dispatch flag sync would charge the guard for the loop style,
    # not the guard work.
    for _ in range(repeats):
        for leg in legs.values():
            with fluid.scope_guard(leg["scope"]):
                t0 = time.perf_counter()
                for _ in range(leg["outer"]):
                    leg["out"] = exe.run(leg["prog"], feed=feed,
                                         fetch_list=[leg["loss"]],
                                         **leg["run_kw"])
                dt = time.perf_counter() - t0
            leg["best"] = dt if leg["best"] is None \
                else min(leg["best"], dt)
    for name, leg in legs.items():
        assert np.isfinite(np.asarray(leg["out"][0])).all(), \
            "non-finite loss in %s leg" % name

    def rate(leg):
        return leg["outer"] * leg["multistep"] / leg["best"]

    plain_off = rate(legs["plain"])
    plain_on = rate(legs["guarded"])
    multi_off = rate(legs["multi"])
    multi_on = rate(legs["multi_guarded"])

    def overhead(off, on):
        return round((off / on - 1.0) * 100.0, 2)

    _emit({
        "metric": "resil_guarded_steps_per_sec",
        "value": round(plain_on, 2),
        "unit": "steps/sec",
        "vs_baseline": None,
        "batch": batch, "layers": n_layers, "hidden": hidden,
        "steps": steps, "multistep": k, "repeats": repeats,
        "plain_steps_per_sec": round(plain_off, 2),
        "guarded_steps_per_sec": round(plain_on, 2),
        "multistep_steps_per_sec": round(multi_off, 2),
        "multistep_guarded_steps_per_sec": round(multi_on, 2),
        "overhead_pct_plain": overhead(plain_off, plain_on),
        "overhead_pct_multistep": overhead(multi_off, multi_on),
        "device": str(jax.devices()[0]),
    })


def bench_sentinel():
    """BENCH_SENTINEL=1: training-health monitoring overhead
    (ARCHITECTURE.md §29). Trains the deep-narrow smoke MLP with the
    sentinel's guard configuration (guards + the grad-norm stat channel)
    and times four legs:

        baseline        the gn-channel program, nothing watching it
        sentinel        same PROGRAM + TrainingSentinel.observe per step
                        (loss z-score + grad-norm z over the stat tap)
        sentinel_canary same + one CanaryChecker dispatch every
                        BENCH_SDC_EVERY steps (the SDC cadence cost)
        nochannel       guards WITHOUT the stat channel (informational:
                        what install_numeric_guards(grad_norm=True)
                        itself adds in-graph)

    The number this leg exists to defend is overhead_pct_sentinel <= 3%
    (test_bench_sentinel_smoke asserts it): the monitor reads a loss the
    loop already fetched and a grad norm that rode an existing transfer,
    so its cost is host arithmetic on two floats. baseline and sentinel
    deliberately run the SAME program (two scopes, one executable) so
    the gated ratio isolates exactly that monitoring cost — XLA:CPU
    run-to-run executable layout variance between two separately
    compiled programs was measured at +-5% on this smoke model, which
    would drown a 3% gate in compile-lottery noise. The in-graph channel
    cost (two executables, unavoidably noisy at smoke scale) is emitted
    as overhead_pct_channel for the benchd TPU tier to track, not gated.

    Knobs: BENCH_STEPS, BENCH_WARMUP, BENCH_BATCH, BENCH_RESIL_LAYERS,
    BENCH_RESIL_HIDDEN, BENCH_SDC_EVERY (canary cadence, default 16),
    BENCH_SENTINEL_REPEATS (timed rounds; per-leg min taken).

    Same deflake discipline as bench_resil (this leg also gates a
    ratio on a shared CI box): the legs are timed in INTERLEAVED
    rounds, each keeping its min across rounds, so a host-contention
    burst slows a whole round together and the min drops the round."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as fluid
    from paddle_tpu.resilience import install_numeric_guards
    from paddle_tpu.resilience.sdc import CanaryChecker
    from paddle_tpu.resilience.sentinel import TrainingSentinel

    batch = int(os.environ.get("BENCH_BATCH", "256"))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "64")))
    warmup = int(os.environ.get("BENCH_WARMUP", "2"))
    n_layers = int(os.environ.get("BENCH_RESIL_LAYERS", "10"))
    hidden = int(os.environ.get("BENCH_RESIL_HIDDEN", "64"))
    sdc_every = max(1, int(os.environ.get("BENCH_SDC_EVERY", "16")))
    repeats = max(1, int(os.environ.get("BENCH_SENTINEL_REPEATS", "5")))

    rng = np.random.RandomState(0)
    xs = jnp.asarray(rng.rand(batch, hidden).astype("float32"))
    ys = jnp.asarray(rng.rand(batch, 1).astype("float32"))
    jax.block_until_ready((xs, ys))
    feed = {"x": xs, "y": ys}

    def build(grad_norm):
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.unique_name.guard(), fluid.program_guard(main_prog,
                                                            startup):
            x = fluid.layers.data(name="x", shape=[hidden],
                                  dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            h = x
            for _ in range(n_layers):
                h = fluid.layers.fc(input=h, size=hidden, act="relu")
            p = fluid.layers.fc(input=h, size=1)
            loss = fluid.layers.mean(
                x=fluid.layers.square_error_cost(input=p, label=y))
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
        install_numeric_guards(main_prog, loss=loss, grad_norm=grad_norm)
        return main_prog, startup, loss

    exe = fluid.Executor(fluid.TPUPlace())

    # detection intentionally lobotomized: the leg measures MONITORING
    # cost, and a real verdict (a z spike, or the divergence trend — a
    # converged loss oscillating around 1e-5 trips a 3x-median factor
    # honestly) would divert a round into recovery bookkeeping
    def fresh_sentinel():
        return TrainingSentinel(window=64, warmup=8, z_threshold=1e9,
                                divergence_patience=10 ** 9)

    canary = CanaryChecker(shape=(64, 64), iters=2)
    canary.record_reference()

    gn_prog, gn_startup, gn_loss = build(True)
    nc_prog, nc_startup, nc_loss = build(False)
    legs = {}
    for name, prog, startup, loss, monitored, with_canary in (
            ("baseline", gn_prog, gn_startup, gn_loss, False, False),
            ("sentinel", gn_prog, gn_startup, gn_loss, True, False),
            ("sentinel_canary", gn_prog, gn_startup, gn_loss, True, True),
            ("nochannel", nc_prog, nc_startup, nc_loss, False, False)):
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(warmup):
                exe.run(prog, feed=feed, fetch_list=[loss])
        legs[name] = {"prog": prog, "loss": loss, "scope": scope,
                      "monitored": monitored, "canary": with_canary,
                      "best": None, "out": None}

    for _ in range(repeats):
        for leg in legs.values():
            sentinel = fresh_sentinel() if leg["monitored"] else None
            with fluid.scope_guard(leg["scope"]):
                t0 = time.perf_counter()
                for i in range(steps):
                    out = exe.run(leg["prog"], feed=feed,
                                  fetch_list=[leg["loss"]])
                    leg["out"] = out
                    if sentinel is not None:
                        gn = exe.last_stats.get("grad_norm")
                        err = sentinel.observe(
                            float(np.asarray(out[0]).reshape(-1)[0]),
                            grad_norm=None if gn is None
                            else float(np.asarray(gn)), step=i)
                        assert err is None, err
                    if leg["canary"] and (i + 1) % sdc_every == 0:
                        canary.check()
                dt = time.perf_counter() - t0
            leg["best"] = dt if leg["best"] is None \
                else min(leg["best"], dt)
    for name, leg in legs.items():
        assert np.isfinite(np.asarray(leg["out"][0])).all(), \
            "non-finite loss in %s leg" % name

    baseline = steps / legs["baseline"]["best"]
    monitored = steps / legs["sentinel"]["best"]
    canaried = steps / legs["sentinel_canary"]["best"]
    nochannel = steps / legs["nochannel"]["best"]

    def overhead(off, on):
        return round((off / on - 1.0) * 100.0, 2)

    _emit({
        "metric": "sentinel_steps_per_sec",
        "value": round(monitored, 2),
        "unit": "steps/sec",
        "vs_baseline": None,
        "batch": batch, "layers": n_layers, "hidden": hidden,
        "steps": steps, "repeats": repeats, "sdc_every": sdc_every,
        "baseline_steps_per_sec": round(baseline, 2),
        "sentinel_steps_per_sec": round(monitored, 2),
        "canary_steps_per_sec": round(canaried, 2),
        "nochannel_steps_per_sec": round(nochannel, 2),
        "overhead_pct_sentinel": overhead(baseline, monitored),
        "overhead_pct_canary": overhead(baseline, canaried),
        "overhead_pct_channel": overhead(nochannel, baseline),
        "canary_checks": int(canary.checks),
        "device": str(jax.devices()[0]),
    })


def _ccache_build_trainer(fluid, dim, layers):
    """The restartable training model both compile-cache children share:
    deep-narrow (dispatch/compile-bound, the cold-start victim), Adam so
    the checkpoint carries realistic state."""
    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main_prog,
                                                        startup):
        x = fluid.layers.data(name="x", shape=[dim], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = x
        for _ in range(layers):
            h = fluid.layers.fc(input=h, size=dim, act="relu")
        p = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.mean(
            x=fluid.layers.square_error_cost(input=p, label=y))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return main_prog, startup, loss


def _ccache_child(kind):
    """One cold-or-warm process start, measured from inside (import and
    device-init time excluded — the cache can't help those; what it
    kills is trace+lower+compile). Prints one JSON line with wall times
    and the always-on compile_cache counters: `compiles` = fresh
    compiles this process paid (each one stores an artifact),
    `aot_hits` = compiles replaced by disk loads."""
    import paddle_tpu as fluid
    from paddle_tpu.core.compile_cache import aot_stats

    dim = int(os.environ.get("BENCH_CCACHE_DIM", "64"))
    layers = int(os.environ.get("BENCH_CCACHE_LAYERS", "10"))
    rng = np.random.RandomState(0)

    if kind == "serving":
        from paddle_tpu.serving import InferenceEngine
        buckets = [int(b) for b in os.environ.get(
            "BENCH_CCACHE_BUCKETS", "1,2,4,8").split(",")]
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.unique_name.guard(), fluid.program_guard(main_prog,
                                                            startup):
            x = fluid.layers.data(name="x", shape=[dim],
                                  dtype="float32")
            h = x
            for _ in range(layers):
                h = fluid.layers.fc(input=h, size=dim, act="relu")
            out = fluid.layers.fc(input=h, size=1)
        infer = main_prog.prune([out.name], for_test=True)
        exe = fluid.Executor(fluid.TPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
        engine = InferenceEngine(
            program=infer, feed_names=["x"], fetch_vars=[out],
            batch_buckets=buckets, warmup=False, validate=False)
        for name in scope.names():
            v = scope.get(name)
            if v is not None:
                engine._scope.set(name, v)
        t0 = time.perf_counter()
        engine.warmup()
        warmup_s = time.perf_counter() - t0
        # steady state stays bit-for-bit correct off the loaded artifacts
        got = engine.run_direct({"x": rng.rand(2, dim).astype("f")})[0]
        engine.close()
        print(json.dumps({
            "kind": kind, "warmup_s": round(warmup_s, 4),
            "buckets": buckets,
            "check": float(np.asarray(got[out.name]).reshape(-1)[0]),
            **{k: v for k, v in aot_stats().items()
               if k in ("stores", "hits", "load_errors")}}))
        return 0

    if kind == "trainer":
        from paddle_tpu.checkpoint import CheckpointManager
        from paddle_tpu.core.utils import device_fetch_barrier
        ckdir = os.environ["BENCH_CCACHE_CKPT_DIR"]
        steps = int(os.environ.get("BENCH_CCACHE_STEPS", "8"))
        batch = int(os.environ.get("BENCH_BATCH", "32"))
        main_prog, startup, loss = _ccache_build_trainer(fluid, dim,
                                                         layers)
        feed = {"x": rng.rand(batch, dim).astype("f"),
                "y": rng.rand(batch, 1).astype("f")}
        exe = fluid.Executor(fluid.TPUPlace())
        scope = fluid.Scope()
        mgr = CheckpointManager(ckdir, async_save=False)
        restored = None
        with fluid.scope_guard(scope):
            exe.run(startup)
            restored = mgr.restore(program=main_prog, scope=scope)
            # the number the cache exists to move: restart/rollback
            # re-entry pays trace+lower+compile before step one — or a
            # disk load
            t0 = time.perf_counter()
            out = exe.run(main_prog, feed=feed, fetch_list=[loss],
                          return_numpy=False)
            device_fetch_barrier(out)
            first_step_s = time.perf_counter() - t0
            for i in range(steps - 1):
                out = exe.run(main_prog, feed=feed, fetch_list=[loss],
                              return_numpy=False)
            device_fetch_barrier(out)
            total_s = time.perf_counter() - t0
            if restored is None:
                mgr.save(steps, program=main_prog, scope=scope,
                         wait=True)
        mgr.close()
        print(json.dumps({
            "kind": kind, "restored_step": restored,
            "first_step_s": round(first_step_s, 4),
            "total_s": round(total_s, 4),
            "loss": float(np.asarray(out[0]).reshape(-1)[0]),
            **{k: v for k, v in aot_stats().items()
               if k in ("stores", "hits", "load_errors")}}))
        return 0

    raise SystemExit("unknown BENCH_COMPILE_CACHE_CHILD=%r" % kind)


def bench_compile_cache():
    """BENCH_COMPILE_CACHE=1: the cold-start legs. Each scenario runs as
    a fresh subprocess twice against ONE persistent AOT cache dir — the
    first (cold) process pays every compile and publishes artifacts,
    the second (warm) process must show ZERO fresh compiles and a
    measured wall-time drop:

      (a) serving warmup over a bucket lattice (the ptpu_serve restart),
      (b) trainer restart + checkpoint-rollback re-entry (the
          resilience Supervisor's recovery path).

    One JSON line per scenario. Knobs: BENCH_CCACHE_DIM /
    BENCH_CCACHE_LAYERS (model size), BENCH_CCACHE_BUCKETS (lattice),
    BENCH_CCACHE_STEPS (trainer steps)."""
    import shutil
    import subprocess
    import tempfile

    workdir = tempfile.mkdtemp(prefix="bench_ccache_")
    aot_dir = os.path.join(workdir, "aot")
    ckpt_dir = os.path.join(workdir, "ckpt")
    os.makedirs(ckpt_dir)

    def run_child(kind):
        env = dict(os.environ)
        env.update({
            "BENCH_COMPILE_CACHE_CHILD": kind,
            "FLAGS_aot_cache_dir": aot_dir,
            # isolate jax's own HLO cache too, so "cold" is honest
            "FLAGS_compile_cache_dir": os.path.join(workdir, "xla"),
            "BENCH_CCACHE_CKPT_DIR": ckpt_dir,
        })
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True,
            timeout=int(os.environ.get("BENCH_CCACHE_TIMEOUT", "600")))
        if out.returncode != 0:
            raise RuntimeError("compile-cache child %r failed:\n%s\n%s"
                               % (kind, out.stdout, out.stderr))
        return json.loads(out.stdout.strip().splitlines()[-1])

    try:
        for kind, metric, field in (
                ("serving", "compile_cache_serving_warmup", "warmup_s"),
                ("trainer", "compile_cache_trainer_restart",
                 "first_step_s")):
            cold = run_child(kind)
            warm = run_child(kind)
            speedup = (cold[field] / warm[field]) if warm[field] else None
            _emit({
                "metric": metric,
                # value must be a number (benchd schema); a zero warm
                # time (speedup indeterminate) reports 0.0, never None
                "value": round(speedup, 2) if speedup else 0.0,
                "unit": "x cold/warm %s" % field,
                "vs_baseline": None,
                "cold": cold, "warm": warm,
                "warm_recompiles": warm["stores"],
            })
            sys.stdout.flush()
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_kernels():
    """BENCH_KERNELS=1: the kernel-floor leg (ARCHITECTURE.md §25) —
    per-op fused-vs-unfused and tuned-vs-default-tile timings plus max
    numeric divergence, one JSON line.

    Gate split (the CPU-vs-TPU measurement discipline): correctness
    (divergence bounds per op + the bf16/int8 serving divergence gate)
    is enforced EVERYWHERE — on CPU the kernels run interpret mode, the
    same code path, so a numerics break fails the leg before it ever
    reaches hardware. Speed is asserted only on real TPU (interpret
    mode is orders slower by construction): at least one op must beat
    its unfused path by BENCH_KERNELS_MIN_SPEEDUP (default 1.2; 0
    disables). The >=1.5x-on->=2-ops ROADMAP claim is the sweep tier-3
    target, recorded in the JSON, not asserted here.

    Dims via BENCH_KERNELS_{SEQ,VOCAB,DIM,BATCH}; defaults are small on
    CPU (a correctness leg must stay inside the tier-1 budget) and
    hot-set-sized on TPU."""
    import tempfile

    import numpy as np

    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu.ops import kernel_config as kc
    from paddle_tpu.ops import pallas_kernels as pk
    from paddle_tpu.parallel.ring_attention import attention_reference
    from paddle_tpu.serving.engine import InferenceEngine
    from paddle_tpu.serving.quantize import divergence_bound
    from paddle_tpu.tuning.autotuner import _time_best

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    repeats = int(os.environ.get("BENCH_KERNELS_REPEATS", "3"))
    t = int(os.environ.get("BENCH_KERNELS_SEQ",
                           "2048" if on_tpu else "32"))
    vocab = int(os.environ.get("BENCH_KERNELS_VOCAB",
                               "32000" if on_tpu else "128"))
    d = int(os.environ.get("BENCH_KERNELS_DIM",
                           "512" if on_tpu else "16"))
    batch = int(os.environ.get("BENCH_KERNELS_BATCH",
                               "8" if on_tpu else "3"))
    rng = np.random.RandomState(0)

    def div(a, b):
        a = np.asarray(a, np.float64)
        b = np.asarray(b, np.float64)
        return float(np.abs(a - b).max() / (np.abs(b).max() + 1e-6))

    per_op = {}

    def leg(name, fused_fn, unfused_fn, args, bound):
        f = jax.jit(fused_fn)
        u = jax.jit(unfused_fn)
        got, want = f(*args), u(*args)
        d_ = div(got, want)
        ft = _time_best(f, args, repeats)
        ut = _time_best(u, args, repeats)
        per_op[name] = {"fused_s": round(ft, 6), "unfused_s": round(ut, 6),
                        "speedup": round(ut / ft, 3),
                        "divergence": d_, "bound": bound}
        if d_ > bound:
            raise RuntimeError("kernel %s divergence %.3e exceeds bound "
                               "%.3e" % (name, d_, bound))

    # attention: fused flash (tuned tiles) vs the dense einsum reference
    h, hd = 4, 64
    q, k, v = (jnp.asarray(rng.randn(batch, t, h, hd), jnp.float32) * 0.3
               for _ in range(3))
    tiles = kc.tiles_for("attn", t)
    leg("attn",
        lambda q, k, v: pk.flash_attention(q, k, v, causal=True,
                                           block_q=tiles["block_q"],
                                           block_k=tiles["block_k"]),
        lambda q, k, v: attention_reference(q, k, v, causal=True),
        (q, k, v), 1e-3)

    # tuned-vs-default tiles (same kernel both sides): only reported
    # when a tuned entry actually changed the tiles
    default_tiles = kc.DEFAULT_TILES["attn"]
    tuned = None
    if tiles != default_tiles:
        tf = _time_best(jax.jit(
            lambda q, k, v: pk.flash_attention(
                q, k, v, causal=True, block_q=tiles["block_q"],
                block_k=tiles["block_k"])), (q, k, v), repeats)
        df = _time_best(jax.jit(
            lambda q, k, v: pk.flash_attention(
                q, k, v, causal=True, block_q=default_tiles["block_q"],
                block_k=default_tiles["block_k"])), (q, k, v), repeats)
        tuned = {"tiles": tiles, "default": default_tiles,
                 "tuned_s": round(tf, 6), "default_s": round(df, 6),
                 "speedup": round(df / tf, 3)}

    # softmax-xent
    n = batch * 32
    logits = jnp.asarray(rng.randn(n, vocab), jnp.float32)
    labels = jnp.asarray(rng.randint(0, vocab, (n,)), jnp.int32)

    def xent_dense(lg, lb):
        lp = jax.nn.log_softmax(lg, axis=-1)
        return -lp[jnp.arange(lg.shape[0]), lb].reshape(-1, 1)

    leg("xent",
        lambda lg, lb: pk.softmax_xent(
            lg, lb, block_n=kc.tiles_for("xent", vocab)["block_n"]),
        xent_dense, (logits, labels), 1e-5)

    # layer norm
    x_ln = jnp.asarray(rng.randn(n, d), jnp.float32)
    scale = jnp.asarray(rng.rand(d) + 0.5, jnp.float32)
    bias = jnp.asarray(rng.randn(d), jnp.float32)

    def ln_dense(x, s, b):
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-5) * s + b

    leg("ln",
        lambda x, s, b: pk.layer_norm(
            x, s, b, block_n=kc.tiles_for("ln", d)["block_n"])[0],
        ln_dense, (x_ln, scale, bias), 1e-4)

    # fused LSTM vs the lax.scan path
    lt = max(8, t // 8)
    x_l = jnp.asarray(rng.randn(batch, lt, 4 * d), jnp.float32) * 0.3
    w_l = jnp.asarray(rng.randn(d, 4 * d), jnp.float32) * 0.2
    b_l = jnp.asarray(rng.randn(4 * d), jnp.float32) * 0.1
    lens = jnp.asarray(rng.randint(1, lt + 1, (batch,)), jnp.int32)

    def lstm_scan(x, w, b, lens):
        tt = x.shape[1]
        m = (jnp.arange(tt)[None, :] < lens[:, None]).astype(jnp.float32)
        xs = jnp.swapaxes(x, 0, 1)
        ms = m.T[:, :, None]
        dd = w.shape[0]
        h0 = jnp.zeros((x.shape[0], dd), jnp.float32)
        c0 = jnp.zeros((x.shape[0], dd), jnp.float32)

        def step(carry, inp):
            h_prev, c_prev = carry
            xt, mt = inp
            gates = xt + h_prev @ w + b
            gc, gi, gf, go = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(gi)
            f = jax.nn.sigmoid(gf)
            c_new = f * c_prev + i * jnp.tanh(gc)
            o = jax.nn.sigmoid(go)
            h_new = o * jnp.tanh(c_new)
            hh = mt * h_new + (1 - mt) * h_prev
            cc = mt * c_new + (1 - mt) * c_prev
            return (hh, cc), hh

        _, hs = jax.lax.scan(step, (h0, c0), (xs, ms))
        return jnp.swapaxes(hs, 0, 1)

    leg("lstm",
        lambda x, w, b, lens: pk.fused_lstm(
            x, w, b, None, None, lens,
            block_b=kc.tiles_for("lstm", d)["block_b"])[0],
        lstm_scan, (x_l, w_l, b_l, lens), 1e-5)

    # masked sequence softmax
    x_s = jnp.asarray(rng.randn(batch * 16, t), jnp.float32)
    lens_s = jnp.asarray(rng.randint(1, t + 1, (batch * 16,)), jnp.int32)

    def seq_dense(x, lens):
        m = (jnp.arange(x.shape[1])[None, :]
             < lens[:, None]).astype(x.dtype)
        return jax.nn.softmax(jnp.where(m > 0, x, -1e30), axis=1) * m

    leg("seq_softmax",
        lambda x, lens: pk.masked_softmax(
            x, lens, block_n=kc.tiles_for("seq", t)["block_n"]),
        seq_dense, (x_s, lens_s), 1e-6)

    # quantized serving divergence gate: tiny MLP, fp32 vs bf16/int8
    # engines over the same weights (run_direct: no batcher noise)
    feat, classes = 16, 4
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 11
    with fluid.unique_name.guard(), fluid.program_guard(main_p, startup):
        xv = fluid.layers.data(name="x", shape=[feat], dtype="float32")
        hv = fluid.layers.fc(input=xv, size=32, act="relu")
        pred = fluid.layers.fc(input=hv, size=classes, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    mdl = tempfile.mkdtemp(prefix="bench_kernels_model_")
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        fluid.io.save_inference_model(mdl, ["x"], [pred], exe, main_p)
    feed = {"x": rng.randn(4, feat).astype("float32")}
    quant = {}
    ref_eng = InferenceEngine(mdl, warmup=False)
    ref_out, _ = ref_eng.run_direct(feed)
    for wd in ("bf16", "int8"):
        eng = InferenceEngine(mdl, weights_dtype=wd, warmup=False)
        out, _ = eng.run_direct(feed)
        dv = max(div(out[nm], ref_out[nm]) for nm in ref_out)
        bound = divergence_bound(wd)
        quant[wd] = {"divergence": dv, "bound": bound,
                     "bytes_before": eng.quantize_report["bytes_before"],
                     "bytes_after": eng.quantize_report["bytes_after"]}
        eng.close()
        if dv > bound:
            ref_eng.close()
            raise RuntimeError("%s serving divergence %.3e exceeds gate "
                               "%.3e" % (wd, dv, bound))
    ref_eng.close()

    speedups = [rec["speedup"] for rec in per_op.values()]
    geomean = float(np.exp(np.mean(np.log(np.maximum(speedups, 1e-9)))))
    min_speedup = float(os.environ.get("BENCH_KERNELS_MIN_SPEEDUP",
                                       "1.2"))
    if on_tpu and min_speedup > 0 and max(speedups) < min_speedup:
        raise RuntimeError(
            "TPU speed gate: no fused op beat its unfused path by %.2fx "
            "(best %.2fx)" % (min_speedup, max(speedups)))
    _emit({
        "metric": "kernel_floor_speedup",
        "value": round(geomean, 3), "unit": "x fused/unfused",
        "vs_baseline": None,
        "device": str(jax.devices()[0]),
        "on_tpu": on_tpu,
        "speed_asserted": bool(on_tpu and min_speedup > 0),
        "ops_ge_1p5x": sum(1 for s in speedups if s >= 1.5),
        "per_op": per_op,
        "tuned_vs_default": tuned,
        "quantized": quant,
        "dims": {"seq": t, "vocab": vocab, "dim": d, "batch": batch}})


def main():
    # compile-cache child processes: spawned by bench_compile_cache with
    # the parent already past the lock/device gates — dispatch BEFORE
    # tpu_guard so a child never deadlocks on the parent's exclusive
    # client lock
    child = os.environ.get("BENCH_COMPILE_CACHE_CHILD")
    if child:
        sys.exit(_ccache_child(child))
    if os.environ.get("BENCH_COMPILE_CACHE") == "1":
        # the parent only orchestrates subprocesses — it must not take
        # the exclusive TPU client lock its own children need (each
        # child acquires it through the normal tpu_guard init hook,
        # sequentially)
        try:
            bench_compile_cache()
        except Exception as e:  # noqa: BLE001 — one JSON error line
            _emit(_error_line(repr(e)))
            sys.stdout.flush()
            sys.exit(3)
        return
    # Exclusive-client lock FIRST, synchronously, with a generous timeout:
    # a wait here means another TPU client (e.g. the 2-min probe loop) is
    # finishing — that is NOT a tunnel wedge and must not eat into the
    # device-init watchdog below.  tpu_guard also hooks jax backend init,
    # so the lock is held either way; this call just fronts the wait.
    from paddle_tpu import tpu_guard
    if not tpu_guard.cpu_only_env():
        try:
            tpu_guard.acquire_tpu_lock(timeout=float(
                os.environ.get("PTPU_LOCK_TIMEOUT", "3600")))
        except tpu_guard.TPULockTimeout as e:
            _emit(_error_line(str(e)))
            sys.stdout.flush()
            os._exit(4)
    # Persistent executable cache: repeat configs (sweep re-runs, the
    # driver's bench) load compiled code from disk instead of burning
    # tunnel time recompiling. Defaulted ON only when warmup excludes
    # compile time from the measurement; warmup=0 is the documented
    # compile-INCLUSIVE mode, and a cache hit there would report
    # near-zero compile cost as throughput. FLAGS_compile_cache_dir
    # overrides either way ('' = explicit off, a path = on).
    from paddle_tpu.core.compile_cache import (default_cache_dir,
                                               maybe_enable_persistent_cache)
    if int(os.environ.get("BENCH_WARMUP", "5")) > 0:
        maybe_enable_persistent_cache(default_cache_dir())
    else:
        maybe_enable_persistent_cache()  # flag-only opt-in
    _await_devices(int(os.environ.get("BENCH_DEVICE_TIMEOUT", "600")))
    # Loud-failure rule: never emit CPU numbers dressed up as TPU data
    # (axon init failure falls back to CPU silently otherwise).
    if tpu_guard.accelerator_missing():
        _emit(_error_line(
            "accelerator expected but only CPU devices initialized"))
        sys.stdout.flush()
        os._exit(3)
    if os.environ.get("BENCH_SERVING") == "1":
        bench_serving()
        return
    if os.environ.get("BENCH_POOL") == "1":
        bench_pool()
        return
    if os.environ.get("BENCH_FLEET") == "1":
        bench_fleet()
        return
    if os.environ.get("BENCH_CKPT") == "1":
        bench_ckpt()
        return
    if os.environ.get("BENCH_RESIL") == "1":
        bench_resil()
        return
    if os.environ.get("BENCH_SENTINEL") == "1":
        bench_sentinel()
        return
    if os.environ.get("BENCH_SHARDED") == "1":
        bench_sharded()
        return
    if os.environ.get("BENCH_TP") == "1":
        bench_tp()
        return
    if os.environ.get("BENCH_PIPELINE") == "1":
        bench_pipeline()
        return
    if os.environ.get("BENCH_OBS") == "1":
        bench_obs()
        return
    if os.environ.get("BENCH_KERNELS") == "1":
        try:
            bench_kernels()
        except Exception as e:  # noqa: BLE001 — one JSON error line
            _emit(_error_line("kernels leg failed: %r" % (e,)))
            sys.stdout.flush()
            os._exit(2)
        return
    if os.environ.get("BENCH_DECODE") == "1" \
            and os.environ.get("BENCH_MODEL", "") != "transformer":
        bench_decode()
        return
    model = os.environ.get("BENCH_MODEL", "resnet50")
    if model == "transformer":
        if os.environ.get("BENCH_DECODE") == "1":
            bench_transformer_decode()
        else:
            bench_transformer()
        return
    if model == "stacked_lstm":
        bench_stacked_lstm()
        return
    import jax
    import paddle_tpu as fluid
    from paddle_tpu.core.utils import device_fetch_barrier
    from paddle_tpu.models.image_classification import build_train

    batch = int(os.environ.get("BENCH_BATCH", "256"))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "20")))
    warmup = int(os.environ.get("BENCH_WARMUP", "5"))
    dtype = os.environ.get("BENCH_DTYPE", "bf16")  # bf16 | fp32
    remat = os.environ.get("BENCH_REMAT", "0") == "1"
    # smoke-run knobs (defaults = the headline config)
    hw = int(os.environ.get("BENCH_IMAGE_HW", "224"))
    class_dim = int(os.environ.get("BENCH_CLASS_DIM", "1000"))
    # feed modes: device (one-time transfer, chip-throughput headline) |
    # host (float32 batches through DoubleBufferReader — measures the
    # full pipeline incl. link bandwidth) | host_u8 (uint8 batches,
    # normalize on device: 4x less traffic — the feeder machinery
    # decoupled from link bandwidth, round-4 weak #5)
    feed_mode = os.environ.get("BENCH_FEED", "device")

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.unique_name.guard(), fluid.program_guard(main_prog, startup):
        image, label, avg_cost, acc = build_train(
            model=model, class_dim=class_dim, image_shape=(3, hw, hw),
            learning_rate=0.1, momentum=0.9, use_bf16=(dtype == "bf16"),
            uint8_input=(feed_mode == "host_u8"))
    if remat:  # trade FLOPs for activation memory (enables larger batch)
        fluid.memory_optimization_transpiler.enable_rematerialization(
            main_prog)

    place = fluid.TPUPlace()
    exe = fluid.Executor(place)
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    import jax.numpy as jnp
    if feed_mode in ("host", "host_u8"):
        # realistic input pipeline: numpy batches staged host→device by the
        # shipped DoubleBufferReader (core/readers.py) — the same code path
        # layers.double_buffer uses — so the copy overlaps the running step
        from itertools import count
        from paddle_tpu.core.readers import (DoubleBufferReader,
                                             IteratorReader)
        def make_image():
            if feed_mode == "host_u8":
                return (rng.rand(batch, 3, hw, hw) * 255).astype("uint8")
            return rng.rand(batch, 3, hw, hw).astype("float32")

        host_batches = [
            (make_image(),
             rng.randint(0, class_dim, (batch, 1)).astype("int32"))
            for _ in range(3)]
        reader = DoubleBufferReader(IteratorReader(
            lambda: (host_batches[i % len(host_batches)] for i in count())),
            capacity=2, place=place)

        def stage(_i):
            img, lbl = reader.next()
            return {"image": img, "label": lbl}

        feeds = None  # per-step, via prefetcher below
    else:
        # one-time host→device transfer; the timed loop feeds
        # device-resident arrays
        xs = jnp.asarray(rng.rand(batch, 3, hw, hw).astype("float32"))
        ys = jnp.asarray(rng.randint(0, class_dim, (batch, 1)).astype("int32"))
        jax.block_until_ready((xs, ys))
        feeds = {"image": xs, "label": ys}

    multistep = _multistep()
    if multistep > 1 and feed_mode != "device":
        # loud-failure rule: the host feed modes exist to measure the
        # input pipeline, but Executor.run(steps=K) REPLAYS an explicit
        # feed for all K steps — the reader would fire once per K-block,
        # crediting K steps of throughput to 1/K of the staging work.
        # (The in-graph-reader path measures the pipeline under the
        # loop honestly; bench.py doesn't build one yet.)
        _emit(_error_line(
            "BENCH_MULTISTEP>1 with BENCH_FEED=%s would replay one "
            "staged batch per K-step block and overstate pipeline "
            "throughput; use BENCH_FEED=device" % feed_mode))
        sys.stdout.flush()
        os._exit(2)
    outer, total_steps = _step_plan(steps, multistep)
    run_kw = _run_kw(multistep)
    with fluid.scope_guard(scope):
        exe.run(startup)
        # warmup=0 is honored: the timed loop then includes compile time
        for _ in range(warmup):
            fd = stage(0) if feeds is None else feeds
            exe.run(main_prog, feed=fd, fetch_list=[avg_cost], **run_kw)
        t0 = time.perf_counter()
        for i in range(outer):
            fd = stage(i) if feeds is None else feeds
            out = exe.run(main_prog, feed=fd,
                          fetch_list=[avg_cost], return_numpy=False,
                          **run_kw)
        device_fetch_barrier(out)
        dt = time.perf_counter() - t0
        loss = np.asarray(out[0])
        assert np.isfinite(loss).all(), "non-finite loss"

    ips = batch * total_steps / dt
    headline = (hw == 224 and class_dim == 1000)
    # ResNet-50 fwd = 4.09 GMACs = 8.18e9 FLOPs @ 224^2 (the commonly
    # quoted "4.1 GFLOPs" is MACs); training ~ 3x fwd. Audited round 4:
    # per-conv program shapes sum to 8.178e9 and XLA cost_analysis counts
    # 8.14e9 fwd / 26.9e9 train — so 3*8.2e9 is the conservative
    # conv+fc-only floor. (The pre-round-4 constant 3*4.1e9 undercounted
    # MFU by 2x.) VGG16: 15.5 GFLOPs fwd.
    flops_per_image, metric = _IMAGE_MODELS.get(
        model, (None, "%s_imagenet_train_throughput" % model))
    rec = {
        "metric": metric,
        "value": round(ips, 2),
        "unit": "images/sec/chip",
        # the 300 img/s V100 baseline is a ResNet-50 224x224/1000-class
        # number; other models/smoke configs must not masquerade as it
        "vs_baseline": round(ips / 300.0, 3)
        if headline and model == "resnet50" else None,
        "batch": batch,
        "dtype": dtype,
        "feed": feed_mode,
        "multistep": multistep,
        "device": str(jax.devices()[0]),
        "mfu": _mfu(ips * flops_per_image)
        if headline and flops_per_image else None,
        "peak_tflops": _peak_tflops(),
        "model": model,
        "loss": float(np.asarray(loss).reshape(-1)[0]),
    }
    if not headline:
        rec["image_hw"] = hw
        rec["class_dim"] = class_dim
    _emit(rec)


if __name__ == "__main__":
    main()
